"""CaiT: Class-Attention in Image Transformers, TPU-native
(reference: timm/models/cait.py:1-632; Touvron et al., 'Going deeper with
Image Transformers').

Two-phase trunk: `depth` self-attention blocks with Talking-Heads attention
over patch tokens only (no cls token), then `depth_token_only` class-attention
blocks where a cls token cross-attends the frozen patch sequence. TPU-first
notes: talking-heads' cross-head mixes are expressed as einsums over the head
axis (two tiny (H, H) matmuls XLA fuses around the softmax), and the
class-attention query is a rank-3 slice so the second phase is O(N) not O(N²).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    DropPath, Dropout, LayerNorm, Mlp, PatchEmbed,
    get_norm_layer, trunc_normal_, zeros_,
)
from ..layers.attention import scaled_dot_product_attention
from ..layers.drop import dropout_rng_key
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['Cait', 'ClassAttn', 'TalkingHeadAttn']


class ClassAttn(nnx.Module):
    """Cls-token-query cross attention (reference cait.py:27-79)."""

    def __init__(self, dim: int, num_heads: int = 8, qkv_bias: bool = False,
                 attn_drop: float = 0.0, proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.q = linear(dim, dim, use_bias=qkv_bias)
        self.k = linear(dim, dim, use_bias=qkv_bias)
        self.v = linear(dim, dim, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        q = self.q(x[:, 0:1]).reshape(B, 1, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        k = self.k(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        v = self.v(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x_cls = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale, fused=False)
        x_cls = x_cls.transpose(0, 2, 1, 3).reshape(B, 1, C)
        x_cls = self.proj(x_cls)
        return self.proj_drop(x_cls)


class TalkingHeadAttn(nnx.Module):
    """MHSA with pre/post-softmax head mixing (reference cait.py:132-182;
    Shazeer et al., 'Talking-Heads Attention')."""

    def __init__(self, dim: int, num_heads: int = 8, qkv_bias: bool = False,
                 attn_drop: float = 0.0, proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_l = linear(num_heads, num_heads)
        self.proj_w = linear(num_heads, num_heads)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0] * self.scale, qkv[1], qkv[2]
        attn = jnp.einsum('bhnd,bhmd->bhnm', q, k)
        # head-mixing linears act on the head axis: move it last, matmul, move back
        attn = self.proj_l(attn.transpose(0, 2, 3, 1)).transpose(0, 3, 1, 2)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.proj_w(attn.transpose(0, 2, 3, 1)).transpose(0, 3, 1, 2)
        attn = self.attn_drop(attn)
        x = jnp.einsum('bhnm,bhmd->bhnd', attn, v)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        x = self.proj(x)
        return self.proj_drop(x)


class LayerScaleBlock(nnx.Module):
    """Self-attn block w/ named gamma layer scale (reference cait.py:184-231)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float = 4.0, qkv_bias: bool = False,
                 proj_drop: float = 0.0, attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 attn_block: Callable = TalkingHeadAttn, mlp_block: Callable = Mlp,
                 init_values: float = 1e-4,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = attn_block(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, attn_drop=attn_drop,
            proj_drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = mlp_block(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                             drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.gamma_1 = nnx.Param(jnp.full((dim,), init_values, param_dtype))
        self.gamma_2 = nnx.Param(jnp.full((dim,), init_values, param_dtype))

    def __call__(self, x):
        x = x + self.drop_path(self.gamma_1[...].astype(x.dtype) * self.attn(self.norm1(x)))
        x = x + self.drop_path(self.gamma_2[...].astype(x.dtype) * self.mlp(self.norm2(x)))
        return x


class LayerScaleBlockClassAttn(nnx.Module):
    """Class-attention block: cls token attends [cls; patches]
    (reference cait.py:81-130)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float = 4.0, qkv_bias: bool = False,
                 proj_drop: float = 0.0, attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 attn_block: Callable = ClassAttn, mlp_block: Callable = Mlp,
                 init_values: float = 1e-4,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = attn_block(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, attn_drop=attn_drop,
            proj_drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = mlp_block(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                             drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.gamma_1 = nnx.Param(jnp.full((dim,), init_values, param_dtype))
        self.gamma_2 = nnx.Param(jnp.full((dim,), init_values, param_dtype))

    def __call__(self, x, x_cls):
        u = jnp.concatenate([x_cls, x], axis=1)
        x_cls = x_cls + self.drop_path(self.gamma_1[...].astype(u.dtype) * self.attn(self.norm1(u)))
        x_cls = x_cls + self.drop_path(self.gamma_2[...].astype(u.dtype) * self.mlp(self.norm2(x_cls)))
        return x_cls


class Cait(nnx.Module):
    """CaiT with the reference's full model contract (reference cait.py:234-480)."""

    def __init__(
            self,
            img_size: int = 224,
            patch_size: int = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'token',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Union[str, Callable] = 'gelu',
            init_values: float = 1e-4,
            depth_token_only: int = 2,
            mlp_ratio_token_only: float = 4.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'token', 'avg')
        norm_layer = get_norm_layer(norm_layer) or partial(LayerNorm, eps=1e-6)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.grad_checkpointing = False

        self.patch_embed = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans,
            embed_dim=embed_dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        num_patches = self.patch_embed.num_patches
        r = self.patch_embed.patch_size[0]

        self.cls_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, 1, embed_dim), param_dtype))
        self.pos_embed = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, num_patches, embed_dim), param_dtype))
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        self.blocks = nnx.List([
            LayerScaleBlock(
                dim=embed_dim, num_heads=num_heads, mlp_ratio=mlp_ratio, qkv_bias=qkv_bias,
                proj_drop=proj_drop_rate, attn_drop=attn_drop_rate, drop_path=drop_path_rate,
                norm_layer=norm_layer, act_layer=act_layer, init_values=init_values,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            for _ in range(depth)
        ])
        self.feature_info = [
            dict(num_chs=embed_dim, reduction=r, module=f'blocks.{i}') for i in range(depth)]

        self.blocks_token_only = nnx.List([
            LayerScaleBlockClassAttn(
                dim=embed_dim, num_heads=num_heads, mlp_ratio=mlp_ratio_token_only,
                qkv_bias=qkv_bias, norm_layer=norm_layer, act_layer=act_layer,
                init_values=init_values, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            for _ in range(depth_token_only)
        ])

        self.norm = norm_layer(embed_dim, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token'}

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def group_matcher(self, coarse: bool = False):
        def _matcher(name):
            if any(name.startswith(n) for n in ('cls_token', 'pos_embed', 'patch_embed')):
                return 0
            elif name.startswith('blocks.'):
                return int(name.split('.')[1]) + 1
            elif name.startswith('blocks_token_only.'):
                to_offset = len(self.blocks) - len(self.blocks_token_only) + 1
                return int(name.split('.')[1]) + to_offset
            elif name.startswith('norm.'):
                return len(self.blocks)
            return float('inf')
        return _matcher

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'token', 'avg')
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        x = x + self.pos_embed[...].astype(x.dtype)
        x = self.pos_drop(x)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        cls_tokens = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (x.shape[0], 1, x.shape[-1]))
        for blk in self.blocks_token_only:
            cls_tokens = blk(x, cls_tokens)
        x = jnp.concatenate([cls_tokens, x], axis=1)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool:
            x = x[:, 1:].mean(axis=1) if self.global_pool == 'avg' else x[:, 0]
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B, H, W, _ = x.shape
        grid = self.patch_embed.grid_size
        x = self.patch_embed(x)
        x = x + self.pos_embed[...].astype(x.dtype)
        x = self.pos_drop(x)

        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x)
            if i in take_indices:
                intermediates.append(self.norm(x) if (norm and self.norm is not None) else x)
        if reshape:
            intermediates = [y.reshape(B, grid[0], grid[1], -1) for y in intermediates]
        if intermediates_only:
            return intermediates

        cls_tokens = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (x.shape[0], 1, x.shape[-1]))
        for blk in self.blocks_token_only:
            cls_tokens = blk(x, cls_tokens)
        x = jnp.concatenate([cls_tokens, x], axis=1)
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.blocks_token_only = nnx.List([])
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model=None):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    out = {k.replace('module.', ''): v for k, v in state_dict.items()}
    return convert_torch_state_dict(out, model)


def _create_cait(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        Cait, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 384, 384),
        'pool_size': None,
        'crop_pct': 1.0,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'cait_xxs24_224.fb_dist_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224)),
    'cait_xxs24_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_xxs36_224.fb_dist_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224)),
    'cait_xxs36_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_xs24_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_s24_224.fb_dist_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224)),
    'cait_s24_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_s36_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_m36_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'cait_m48_448.fb_dist_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448)),
    'test_cait.untrained': _cfg(input_size=(3, 96, 96)),
})


@register_model
def cait_xxs24_224(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=192, depth=24, num_heads=4, init_values=1e-5)
    return _create_cait('cait_xxs24_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_xxs24_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=192, depth=24, num_heads=4, init_values=1e-5)
    return _create_cait('cait_xxs24_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_xxs36_224(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=192, depth=36, num_heads=4, init_values=1e-5)
    return _create_cait('cait_xxs36_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_xxs36_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=192, depth=36, num_heads=4, init_values=1e-5)
    return _create_cait('cait_xxs36_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_xs24_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=288, depth=24, num_heads=6, init_values=1e-5)
    return _create_cait('cait_xs24_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_s24_224(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=384, depth=24, num_heads=8, init_values=1e-5)
    return _create_cait('cait_s24_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_s24_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=384, depth=24, num_heads=8, init_values=1e-5)
    return _create_cait('cait_s24_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_s36_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=384, depth=36, num_heads=8, init_values=1e-6)
    return _create_cait('cait_s36_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_m36_384(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=768, depth=36, num_heads=16, init_values=1e-6)
    return _create_cait('cait_m36_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def cait_m48_448(pretrained=False, **kwargs) -> Cait:
    model_args = dict(patch_size=16, embed_dim=768, depth=48, num_heads=16, init_values=1e-6)
    return _create_cait('cait_m48_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_cait(pretrained=False, **kwargs) -> Cait:
    model_args = dict(
        img_size=96, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        init_values=1e-5, depth_token_only=1)
    return _create_cait('test_cait', pretrained=pretrained, **dict(model_args, **kwargs))
