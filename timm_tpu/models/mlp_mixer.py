"""MLP-Mixer / gMLP (reference: timm/models/mlp_mixer.py:1-880), TPU-native."""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    DropPath, Dropout, GatedMlp, GluMlp, LayerNorm, Mlp, PatchEmbed,
    calculate_drop_path_rates, get_norm_layer, global_pool_nlc, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['MlpMixer', 'MixerBlock', 'SpatialGatingUnit']


class MixerBlock(nnx.Module):
    """token-mixing MLP over N + channel-mixing MLP over C (reference mlp_mixer.py MixerBlock)."""

    def __init__(
            self,
            dim: int,
            seq_len: int,
            mlp_ratio=(0.5, 4.0),
            mlp_layer: Callable = Mlp,
            norm_layer: Callable = LayerNorm,
            act_layer: Union[str, Callable] = 'gelu',
            drop: float = 0.0,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        tokens_dim, channels_dim = [int(x * dim) for x in mlp_ratio]
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.mlp_tokens = mlp_layer(seq_len, tokens_dim, act_layer=act_layer, drop=drop,
                                    dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp_channels = mlp_layer(dim, channels_dim, act_layer=act_layer, drop=drop,
                                      dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = x + self.drop_path1(self.mlp_tokens(self.norm1(x).transpose(0, 2, 1)).transpose(0, 2, 1))
        x = x + self.drop_path2(self.mlp_channels(self.norm2(x)))
        return x


class SpatialGatingUnit(nnx.Module):
    """gMLP spatial gating (reference mlp_mixer.py SpatialGatingUnit)."""

    def __init__(self, dim: int, seq_len: int, norm_layer: Callable = LayerNorm, *,
                 dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        gate_dim = dim // 2
        self.norm = norm_layer(gate_dim, rngs=rngs)
        self.proj = nnx.Linear(
            seq_len, seq_len, kernel_init=nnx.initializers.normal(1e-6), bias_init=nnx.initializers.ones,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        u, v = jnp.split(x, 2, axis=-1)
        v = self.norm(v)
        v = self.proj(v.transpose(0, 2, 1)).transpose(0, 2, 1)
        return u * v


class SpatialGatingBlock(nnx.Module):
    def __init__(
            self,
            dim: int,
            seq_len: int,
            mlp_ratio: float = 4.0,
            norm_layer: Callable = LayerNorm,
            act_layer: Union[str, Callable] = 'gelu',
            drop: float = 0.0,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        channel_dim = int(dim * mlp_ratio)
        self.norm = norm_layer(dim, rngs=rngs)
        sgu = partial(SpatialGatingUnit, seq_len=seq_len, dtype=dtype, param_dtype=param_dtype)
        self.mlp_channels = GatedMlp(
            dim, channel_dim, act_layer=act_layer, gate_layer=lambda d, rngs: sgu(d, rngs=rngs),
            drop=drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        return x + self.drop_path(self.mlp_channels(self.norm(x)))


class MlpMixer(nnx.Module):
    def __init__(
            self,
            num_classes: int = 1000,
            img_size: int = 224,
            in_chans: int = 3,
            patch_size: int = 16,
            num_blocks: int = 8,
            embed_dim: int = 512,
            mlp_ratio=(0.5, 4.0),
            block_layer: Callable = MixerBlock,
            mlp_layer: Callable = Mlp,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Union[str, Callable] = 'gelu',
            drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            stem_norm: bool = False,
            global_pool: str = 'avg',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.grad_checkpointing = False
        self.global_pool = global_pool
        norm_layer = get_norm_layer(norm_layer) or LayerNorm

        self.stem = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans, embed_dim=embed_dim,
            norm_layer=norm_layer if stem_norm else None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        reduction = self.stem.patch_size[0]
        dpr = calculate_drop_path_rates(drop_path_rate, num_blocks)
        self.blocks = nnx.List([
            block_layer(
                embed_dim,
                self.stem.num_patches,
                mlp_ratio=mlp_ratio,
                mlp_layer=mlp_layer,
                norm_layer=norm_layer,
                act_layer=act_layer,
                drop=proj_drop_rate,
                drop_path=dpr[i],
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            ) if block_layer is MixerBlock else block_layer(
                embed_dim,
                self.stem.num_patches,
                mlp_ratio=mlp_ratio if not isinstance(mlp_ratio, (tuple, list)) else 4.0,
                norm_layer=norm_layer,
                act_layer=act_layer,
                drop=proj_drop_rate,
                drop_path=dpr[i],
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(num_blocks)
        ])
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=reduction) for i in range(num_blocks)]
        self.norm = norm_layer(embed_dim, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=zeros_, bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'avg', 'max', 'avgmax')
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def forward_features(self, x):
        x = self.stem(x)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.norm(x)

    def forward_head(self, x, pre_logits: bool = False):
        x = global_pool_nlc(x, pool_type=self.global_pool, num_prefix_tokens=0)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B, H, W, _ = x.shape
        grid = self.stem.dynamic_feat_size((H, W))
        x = self.stem(x)
        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x)
            if i in take_indices:
                y = self.norm(x) if norm else x
                if output_fmt == 'NHWC':
                    y = y.reshape(B, grid[0], grid[1], -1)
                intermediates.append(y)
        if intermediates_only:
            return intermediates
        x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = LayerNorm(self.embed_dim, rngs=nnx.Rngs(0))
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'stem.proj',
        'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'mixer_s32_224.untrained': _cfg(),
    'mixer_s16_224.untrained': _cfg(),
    'mixer_b32_224.untrained': _cfg(),
    'mixer_b16_224.goog_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'mixer_l16_224.goog_in21k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'gmlp_s16_224.ra3_in1k': _cfg(hf_hub_id='timm/'),
    'test_mixer.untrained': _cfg(input_size=(3, 160, 160)),
})


def _create_mixer(variant, pretrained=False, **kwargs):
    from ._torch_convert import convert_torch_state_dict
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        MlpMixer, variant, pretrained,
        pretrained_filter_fn=convert_torch_state_dict,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def mixer_s32_224(pretrained=False, **kwargs) -> MlpMixer:
    return _create_mixer('mixer_s32_224', pretrained, **dict(dict(patch_size=32, num_blocks=8, embed_dim=512), **kwargs))


@register_model
def mixer_s16_224(pretrained=False, **kwargs) -> MlpMixer:
    return _create_mixer('mixer_s16_224', pretrained, **dict(dict(patch_size=16, num_blocks=8, embed_dim=512), **kwargs))


@register_model
def mixer_b32_224(pretrained=False, **kwargs) -> MlpMixer:
    return _create_mixer('mixer_b32_224', pretrained, **dict(dict(patch_size=32, num_blocks=12, embed_dim=768), **kwargs))


@register_model
def mixer_b16_224(pretrained=False, **kwargs) -> MlpMixer:
    return _create_mixer('mixer_b16_224', pretrained, **dict(dict(patch_size=16, num_blocks=12, embed_dim=768), **kwargs))


@register_model
def mixer_l16_224(pretrained=False, **kwargs) -> MlpMixer:
    return _create_mixer('mixer_l16_224', pretrained, **dict(dict(patch_size=16, num_blocks=24, embed_dim=1024), **kwargs))


@register_model
def gmlp_s16_224(pretrained=False, **kwargs) -> MlpMixer:
    model_args = dict(
        patch_size=16, num_blocks=30, embed_dim=256, mlp_ratio=6.0, block_layer=SpatialGatingBlock)
    return _create_mixer('gmlp_s16_224', pretrained, **dict(model_args, **kwargs))


@register_model
def test_mixer(pretrained=False, **kwargs) -> MlpMixer:
    model_args = dict(img_size=160, patch_size=16, num_blocks=2, embed_dim=64)
    return _create_mixer('test_mixer', pretrained, **dict(model_args, **kwargs))
