"""ResNet / ResNeXt / SE-ResNet, TPU-native NHWC.

Re-designed from the reference (timm/models/resnet.py:1-2266). BatchNorm here
is natively a SyncBN under pjit (stats reduce over the global sharded batch),
so the reference's convert_sync_batchnorm/distribute_bn machinery is absent
by design (see timm_tpu/layers/norm.py BatchNorm2d).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Type, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    AvgPool2dAA, BatchNormAct2d, BlurPool2d, ClassifierHead, DropPath, EcaModule,
    SEModule, calculate_drop_path_rates, create_conv2d, get_aa_layer, get_act_fn,
    get_attn, get_norm_act_layer,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['ResNet', 'BasicBlock', 'Bottleneck']


def avg_pool2d(x, kernel: int = 2, stride: int = 2, pad_same: bool = False):
    """NHWC average pool (count_include_pad=False semantics, matching the
    reference's AvgPool2d in downsample_avg, resnet.py:324)."""
    import jax
    padding = 'SAME' if pad_same else 'VALID'
    window = (1, kernel, kernel, 1)
    strides = (1, stride, stride, 1)
    out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, padding)
    if pad_same:
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding)
        return out / counts
    return out / (kernel * kernel)


def max_pool2d(x, kernel: int = 3, stride: int = 2, padding=None):
    """NHWC max pool; default symmetric pad (k-1)//2 on both sides (torch
    semantics — SAME pads right-only for even inputs and shifts windows)."""
    import jax
    neg = -jnp.inf if x.dtype == jnp.float32 else jnp.finfo(x.dtype).min
    if padding is None:
        p = (kernel - 1) // 2
        padding = ((0, 0), (p, p), (p, p), (0, 0))
    x = jnp.pad(x, padding, constant_values=neg)
    return jax.lax.reduce_window(
        x, neg, jax.lax.max, (1, kernel, kernel, 1), (1, stride, stride, 1), 'VALID')


class DownsampleConv(nnx.Module):
    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, dilation=1, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        norm_layer = norm_layer or BatchNormAct2d
        kernel_size = 1 if stride == 1 and dilation == 1 else kernel_size
        first_dilation = (dilation or 1) if kernel_size > 1 else 1
        self.conv = create_conv2d(
            in_chs, out_chs, kernel_size, stride=stride, dilation=first_dilation, padding=None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.bn(self.conv(x))


class DownsampleAvg(nnx.Module):
    """avg-pool + 1x1 conv downsample ('d' variants, reference resnet.py downsample_avg)."""

    def __init__(self, in_chs, out_chs, stride=1, dilation=1, norm_layer=None, *, dtype=None, param_dtype=jnp.float32, rngs):
        norm_layer = norm_layer or BatchNormAct2d
        self.pool_stride = stride if dilation == 1 else 1
        self.conv = create_conv2d(in_chs, out_chs, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.pool_stride > 1:
            x = avg_pool2d(x, 2, self.pool_stride, pad_same=True)
        return self.bn(self.conv(x))


class BasicBlock(nnx.Module):
    expansion = 1

    def __init__(
            self,
            inplanes: int,
            planes: int,
            stride: int = 1,
            downsample=None,
            cardinality: int = 1,
            base_width: int = 64,
            reduce_first: int = 1,
            dilation: int = 1,
            first_dilation: Optional[int] = None,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            attn_layer: Optional[Callable] = None,
            aa_layer: Optional[Callable] = None,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert cardinality == 1 and base_width == 64, 'BasicBlock only supports default cardinality/width'
        first_planes = planes // reduce_first
        outplanes = planes * self.expansion
        first_dilation = first_dilation or dilation
        use_aa = aa_layer is not None and (stride == 2 or first_dilation != dilation)

        self.conv1 = create_conv2d(
            inplanes, first_planes, 3, stride=1 if use_aa else stride,
            dilation=first_dilation, padding=None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(first_planes, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=first_planes, stride=stride, rngs=rngs) if use_aa else None
        self.conv2 = create_conv2d(
            first_planes, outplanes, 3, dilation=dilation, padding=None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(outplanes, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = attn_layer(outplanes, dtype=dtype, param_dtype=param_dtype, rngs=rngs) if attn_layer else None
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.bn2, 'scale'):
            self.bn2.scale[...] = jnp.zeros_like(self.bn2.scale[...])

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv1(x))
        if self.aa is not None:
            x = self.aa(x)
        x = self.bn2(self.conv2(x))
        if self.se is not None:
            x = self.se(x)
        x = self.drop_path(x)
        if self.downsample is not None:
            shortcut = self.downsample(shortcut)
        return self.act(x + shortcut)


class Bottleneck(nnx.Module):
    expansion = 4

    def __init__(
            self,
            inplanes: int,
            planes: int,
            stride: int = 1,
            downsample=None,
            cardinality: int = 1,
            base_width: int = 64,
            reduce_first: int = 1,
            dilation: int = 1,
            first_dilation: Optional[int] = None,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            attn_layer: Optional[Callable] = None,
            aa_layer: Optional[Callable] = None,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        width = int(math.floor(planes * (base_width / 64)) * cardinality)
        first_planes = width // reduce_first
        outplanes = planes * self.expansion
        first_dilation = first_dilation or dilation
        use_aa = aa_layer is not None and (stride == 2 or first_dilation != dilation)

        self.conv1 = create_conv2d(inplanes, first_planes, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(first_planes, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv2 = create_conv2d(
            first_planes, width, 3, stride=1 if use_aa else stride,
            dilation=first_dilation, groups=cardinality,
            padding=None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(width, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=width, stride=stride, rngs=rngs) if use_aa else None
        self.conv3 = create_conv2d(width, outplanes, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn3 = norm_layer(outplanes, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = attn_layer(outplanes, dtype=dtype, param_dtype=param_dtype, rngs=rngs) if attn_layer else None
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.bn3, 'scale'):
            self.bn3.scale[...] = jnp.zeros_like(self.bn3.scale[...])

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv1(x))
        x = self.bn2(self.conv2(x))
        if self.aa is not None:
            x = self.aa(x)
        x = self.bn3(self.conv3(x))
        if self.se is not None:
            x = self.se(x)
        x = self.drop_path(x)
        if self.downsample is not None:
            shortcut = self.downsample(shortcut)
        return self.act(x + shortcut)


class ResNet(nnx.Module):
    def __init__(
            self,
            block: Union[Type[BasicBlock], Type[Bottleneck], str] = Bottleneck,
            layers: Tuple[int, ...] = (3, 4, 6, 3),
            channels: Tuple[int, ...] = (64, 128, 256, 512),
            num_classes: int = 1000,
            in_chans: int = 3,
            output_stride: int = 32,
            global_pool: str = 'avg',
            cardinality: int = 1,
            base_width: int = 64,
            stem_width: int = 64,
            stem_type: str = '',
            replace_stem_pool: bool = False,
            avg_down: bool = False,
            block_reduce_first: int = 1,
            down_kernel_size: int = 1,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            se_layer: Optional[Callable] = None,
            aa_layer: Optional[Callable] = None,
            block_args: Optional[Dict[str, Any]] = None,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            zero_init_last: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if isinstance(block, str):
            block = {'basic': BasicBlock, 'bottleneck': Bottleneck}[block.lower()]
        assert output_stride in (8, 16, 32)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        block_args = dict(block_args) if block_args else {}
        if 'attn_layer' in block_args:
            se_layer = se_layer or get_attn(block_args.pop('attn_layer'))
        aa_layer = get_aa_layer(aa_layer)
        if isinstance(norm_layer, str):
            norm_layer = get_norm_act_layer(norm_layer, act_layer=act_layer)

        # stem
        deep_stem = 'deep' in stem_type
        inplanes = stem_width * 2 if deep_stem else 64
        if deep_stem:
            stem_chs = (stem_width, stem_width)
            if 'tiered' in stem_type:
                stem_chs = (3 * (stem_width // 4), stem_width)
            self.conv1 = nnx.List([
                create_conv2d(in_chans, stem_chs[0], 3, stride=2, padding=None,
                              dtype=dtype, param_dtype=param_dtype, rngs=rngs),
                create_conv2d(stem_chs[0], stem_chs[1], 3, padding=None,
                              dtype=dtype, param_dtype=param_dtype, rngs=rngs),
                create_conv2d(stem_chs[1], inplanes, 3, padding=None,
                              dtype=dtype, param_dtype=param_dtype, rngs=rngs),
            ])
            self.bn_stem = nnx.List([
                norm_layer(stem_chs[0], act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs),
                norm_layer(stem_chs[1], act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs),
            ])
        else:
            self.conv1 = create_conv2d(
                in_chans, inplanes, 7, stride=2, padding=None,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.bn_stem = None
        self.bn1 = norm_layer(inplanes, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.feature_info = [dict(num_chs=inplanes, reduction=2, module='bn1')]

        # stem pooling: default 3x3/s2 max pool, optionally replaced by a
        # strided conv (+norm/act) or augmented with anti-aliasing
        # (reference resnet.py:561-577)
        if replace_stem_pool:
            stem_pool_max = False
            stem_pool_conv = create_conv2d(
                inplanes, inplanes, 3, stride=1 if aa_layer else 2, padding=None,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            stem_pool_aa = aa_layer(channels=inplanes, stride=2, rngs=rngs) if aa_layer is not None else None
            stem_pool_norm = norm_layer(
                inplanes, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        elif aa_layer is not None:
            stem_pool_conv = stem_pool_norm = None
            if aa_layer is AvgPool2dAA:
                stem_pool_max = False
                stem_pool_aa = AvgPool2dAA(stride=2, rngs=rngs)
            else:
                stem_pool_max = 'stride1'
                stem_pool_aa = aa_layer(channels=inplanes, stride=2, rngs=rngs)
        else:
            stem_pool_conv = stem_pool_norm = stem_pool_aa = None
            stem_pool_max = True
        self.stem_pool_conv = stem_pool_conv
        self.stem_pool_norm = stem_pool_norm
        self.stem_pool_aa = stem_pool_aa
        self.stem_pool_max = stem_pool_max

        # stages
        stage_blocks = []
        total_blocks = sum(layers)
        dpr = calculate_drop_path_rates(drop_path_rate, list(layers), stagewise=True)
        net_stride = 4
        dilation = 1
        for stage_idx, (planes, num_blocks) in enumerate(zip(channels, layers)):
            stride = 1 if stage_idx == 0 else 2
            if net_stride >= output_stride and stride > 1:
                dilation *= stride
                stride = 1
            else:
                net_stride *= stride
            downsample = None
            if stride != 1 or inplanes != planes * block.expansion:
                if avg_down:
                    downsample = DownsampleAvg(
                        inplanes, planes * block.expansion, stride=stride, dilation=dilation,
                        norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
                else:
                    downsample = DownsampleConv(
                        inplanes, planes * block.expansion, kernel_size=down_kernel_size,
                        stride=stride, dilation=dilation,
                        norm_layer=norm_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            blocks = []
            for block_idx in range(num_blocks):
                blocks.append(block(
                    inplanes,
                    planes,
                    stride=stride if block_idx == 0 else 1,
                    downsample=downsample if block_idx == 0 else None,
                    cardinality=cardinality,
                    base_width=base_width,
                    reduce_first=block_reduce_first,
                    dilation=dilation,
                    act_layer=act_layer,
                    norm_layer=norm_layer,
                    attn_layer=se_layer,
                    aa_layer=aa_layer,
                    drop_path=dpr[stage_idx][block_idx],
                    dtype=dtype,
                    param_dtype=param_dtype,
                    rngs=rngs,
                    **block_args,
                ))
                inplanes = planes * block.expansion
            stage_blocks.append(nnx.List(blocks))
            self.feature_info.append(dict(
                num_chs=inplanes, reduction=net_stride, module=f'layer{stage_idx + 1}'))
        self.layer1, self.layer2, self.layer3, self.layer4 = stage_blocks

        self.num_features = self.head_hidden_size = inplanes
        self.head = ClassifierHead(
            self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False

        if zero_init_last:
            for stage in stage_blocks:
                for b in stage:
                    if hasattr(b, 'zero_init_last'):
                        b.zero_init_last()

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv1|^bn1|^bn_stem',
            blocks=r'^layer(\d+)' if coarse else r'^layer(\d+)\.(\d+)',
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def _stem(self, x):
        if self.bn_stem is not None:
            x = self.bn_stem[0](self.conv1[0](x))
            x = self.bn_stem[1](self.conv1[1](x))
            x = self.conv1[2](x)
        else:
            x = self.conv1(x)
        x = self.bn1(x)
        # stem pooling variants (see __init__)
        if getattr(self, 'stem_pool_conv', None) is not None:
            x = self.stem_pool_conv(x)
            if self.stem_pool_aa is not None:
                x = self.stem_pool_aa(x)
            return self.stem_pool_norm(x)
        pool_max = getattr(self, 'stem_pool_max', True)
        if pool_max == 'stride1':
            x = max_pool2d(x, 3, 1)
        elif pool_max:
            x = max_pool2d(x, 3, 2)
        if getattr(self, 'stem_pool_aa', None) is not None:
            x = self.stem_pool_aa(x)
        return x

    def _stages(self):
        return [self.layer1, self.layer2, self.layer3, self.layer4]

    def forward_features(self, x):
        x = self._stem(x)
        for stage in self._stages():
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        stages = self._stages()
        take_indices, max_index = feature_take_indices(len(stages) + 1, indices)
        intermediates = []
        x = self._stem(x)
        if 0 in take_indices:
            intermediates.append(x)
        for i, stage in enumerate(stages):
            if not stop_early or i <= max_index - 1:
                for b in stage:
                    x = b(x)
                if (i + 1) in take_indices:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(5, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv1',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'resnet18.a1_in1k': _cfg(hf_hub_id='timm/'),
    'resnet26.bt_in1k': _cfg(hf_hub_id='timm/'),
    'resnet34.a1_in1k': _cfg(hf_hub_id='timm/'),
    'resnet50.a1_in1k': _cfg(hf_hub_id='timm/'),
    'resnet50d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'resnet101.a1_in1k': _cfg(hf_hub_id='timm/'),
    'resnet152.a1_in1k': _cfg(hf_hub_id='timm/'),
    'resnext50_32x4d.a1_in1k': _cfg(hf_hub_id='timm/'),
    'wide_resnet50_2.racm_in1k': _cfg(hf_hub_id='timm/'),
    'seresnet50.ra2_in1k': _cfg(hf_hub_id='timm/'),
    'test_resnet.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    # tail variants (cfg values ported exactly from reference resnet.py
    # default_cfgs; _ttcfg = timm-trained default: test 288px @ 0.95)
    'resnet10t.c3_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 176, 176),
                              pool_size=(6, 6), test_input_size=(3, 224, 224), test_crop_pct=0.95),
    'resnet14t.c3_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 176, 176),
                              pool_size=(6, 6), test_input_size=(3, 224, 224), test_crop_pct=0.95),
    'resnet18d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0',
                               test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'resnet26d.bt_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0',
                              test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'resnet26t.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                               pool_size=(8, 8), crop_pct=0.94, test_input_size=(3, 320, 320),
                               test_crop_pct=1.0),
    'resnet34d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0',
                               test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'resnet50t.untrained': _cfg(first_conv='conv1.0', test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'resnet101d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                                pool_size=(8, 8), crop_pct=0.95, test_input_size=(3, 320, 320),
                                test_crop_pct=1.0),
    'resnet152d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                                pool_size=(8, 8), crop_pct=0.95, test_input_size=(3, 320, 320),
                                test_crop_pct=1.0),
    'resnet200.untrained': _cfg(test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'resnet200d.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                                pool_size=(8, 8), crop_pct=0.95, test_input_size=(3, 320, 320),
                                test_crop_pct=1.0),
    'resnext50d_32x4d.bt_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'resnext101_32x4d.fb_ssl_yfcc100m_ft_in1k': _cfg(hf_hub_id='timm/'),
    'resnext101_32x8d.fb_wsl_ig1b_ft_in1k': _cfg(hf_hub_id='timm/'),
    'resnext101_32x16d.fb_wsl_ig1b_ft_in1k': _cfg(hf_hub_id='timm/'),
    'resnext101_64x4d.c1_in1k': _cfg(hf_hub_id='timm/'),
    'wide_resnet101_2.tv2_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 176, 176), pool_size=(6, 6),
        test_input_size=(3, 224, 224), test_crop_pct=0.965),
    'seresnet34.untrained': _cfg(),
    'seresnet50t.untrained': _cfg(first_conv='conv1.0'),
    'seresnet101.untrained': _cfg(),
    'seresnet152.untrained': _cfg(),
    'seresnext26d_32x4d.bt_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'seresnext26t_32x4d.bt_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'seresnext50_32x4d.racm_in1k': _cfg(hf_hub_id='timm/'),
    'seresnext101_32x4d.untrained': _cfg(),
    'seresnext101_32x8d.ah_in1k': _cfg(
        hf_hub_id='timm/', crop_pct=0.95, test_input_size=(3, 288, 288), test_crop_pct=1.0),
    'seresnext101_64x4d.gluon_in1k': _cfg(hf_hub_id='timm/'),
    'ecaresnet26t.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                                  pool_size=(8, 8), test_input_size=(3, 320, 320), test_crop_pct=0.95),
    'ecaresnet50d.miil_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'ecaresnet50t.ra2_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0', input_size=(3, 256, 256),
                                  test_input_size=(3, 320, 320), crop_pct=0.95),
    'ecaresnet101d.miil_in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'ecaresnetlight.miil_in1k': _cfg(hf_hub_id='timm/'),
    'resnet50c.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet50s.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet101c.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet101s.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet152c.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet152s.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnet50_gn.a1h_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.94, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1', classifier='fc'),
    'resnext101_32x32d.fb_wsl_ig1b_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, interpolation='bilinear', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1', classifier='fc'),
    'ecaresnet50d_pruned.miil_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'ecaresnet101d_pruned.miil_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'ecaresnet200d.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'ecaresnet269d.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 352, 352), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'ecaresnext26t_32x4d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'ecaresnext50t_32x4d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'seresnet18.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1', classifier='fc'),
    'seresnet152d.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnet200d.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'seresnet269d.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'seresnext101d_32x8d.ah_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'senet154.gluon_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'resnetblur18.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1', classifier='fc'),
    'resnetblur50.bt_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1', classifier='fc'),
    'resnetblur50d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'resnetblur101d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'resnetaa34d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'resnetaa50.a1h_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1', classifier='fc'),
    'resnetaa50d.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'resnetaa50d.sw_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'resnetaa50d.d_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'resnetaa101d.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'resnetaa101d.sw_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnetaa50d.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='conv1.0', classifier='fc'),
    'seresnextaa101d_32x8d.sw_in12k_ft_in1k_288': _cfg(hf_hub_id='timm/', input_size=(3, 288, 288), pool_size=(9, 9), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnextaa101d_32x8d.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnextaa101d_32x8d.sw_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnextaa101d_32x8d.ah_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'seresnextaa201d_32x8d.sw_in12k_ft_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv1.0', classifier='fc'),
    'seresnextaa201d_32x8d.sw_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 384, 384), test_crop_pct=1.0, first_conv='conv1.0', classifier='fc'),
    'resnetrs50.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), pool_size=(5, 5), crop_pct=0.91, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 224, 224), first_conv='conv1.0', classifier='fc'),
    'resnetrs101.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 192, 192), pool_size=(6, 6), crop_pct=0.94, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), first_conv='conv1.0', classifier='fc'),
    'resnetrs152.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), first_conv='conv1.0', classifier='fc'),
    'resnetrs200.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), first_conv='conv1.0', classifier='fc'),
    'resnetrs270.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 352, 352), first_conv='conv1.0', classifier='fc'),
    'resnetrs350.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 288, 288), pool_size=(9, 9), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 384, 384), first_conv='conv1.0', classifier='fc'),
    'resnetrs420.tf_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 416, 416), first_conv='conv1.0', classifier='fc'),
})


def checkpoint_filter_fn(state_dict, model):
    """Map reference-timm resnet names → this module's layout, then apply the
    generic torch→nnx conversion (reference resnet state dicts use Sequential
    indices for downsample and a top-level `fc` head)."""
    import re
    from ._torch_convert import convert_torch_state_dict
    # avg-down models use Sequential(pool, conv, bn) → indices 1/2
    has_avg_down = any('downsample.2.' in k for k in state_dict)
    # replace_stem_pool / aa stems: maxpool is Sequential(conv[, aa], norm, act)
    pool_norm_idx = 2 if any(k.startswith('maxpool.2.') for k in state_dict) else 1
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'^fc\.', 'head.fc.', k)
        k = re.sub(r'^maxpool\.0\.', 'stem_pool_conv.', k)
        k = re.sub(r'^maxpool\.%d\.' % pool_norm_idx, 'stem_pool_norm.', k)
        if has_avg_down:
            k = re.sub(r'(layer\d+\.\d+\.downsample)\.1\.', r'\1.conv.', k)
            k = re.sub(r'(layer\d+\.\d+\.downsample)\.2\.', r'\1.bn.', k)
        else:
            k = re.sub(r'(layer\d+\.\d+\.downsample)\.0\.', r'\1.conv.', k)
            k = re.sub(r'(layer\d+\.\d+\.downsample)\.1\.', r'\1.bn.', k)
        # deep stem Sequential(conv,bn,act,conv,bn,act,conv) → conv1.*/bn_stem.*
        k = re.sub(r'^conv1\.1\.', 'bn_stem.0.', k)
        k = re.sub(r'^conv1\.3\.', 'conv1.1.', k)
        k = re.sub(r'^conv1\.4\.', 'bn_stem.1.', k)
        k = re.sub(r'^conv1\.6\.', 'conv1.2.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_resnet(variant: str, pretrained: bool = False, **kwargs) -> ResNet:
    return build_model_with_cfg(
        ResNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **kwargs,
    )


@register_model
def resnet18(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(2, 2, 2, 2))
    return _create_resnet('resnet18', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet26(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(2, 2, 2, 2))
    return _create_resnet('resnet26', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet34(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(3, 4, 6, 3))
    return _create_resnet('resnet34', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3))
    return _create_resnet('resnet50', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet50d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet101(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3))
    return _create_resnet('resnet101', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet152(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 8, 36, 3))
    return _create_resnet('resnet152', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext50_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), cardinality=32, base_width=4)
    return _create_resnet('resnext50_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def wide_resnet50_2(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), base_width=128)
    return _create_resnet('wide_resnet50_2', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet50(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), se_layer=SEModule)
    return _create_resnet('seresnet50', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet10t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(1, 1, 1, 1), stem_width=32, stem_type='deep_tiered', avg_down=True)
    return _create_resnet('resnet10t', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet14t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(1, 1, 1, 1), stem_width=32, stem_type='deep_tiered', avg_down=True)
    return _create_resnet('resnet14t', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet18d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(2, 2, 2, 2), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet18d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet26d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(2, 2, 2, 2), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet26d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet26t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(2, 2, 2, 2), stem_width=32, stem_type='deep_tiered', avg_down=True)
    return _create_resnet('resnet26t', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet34d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet34d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep_tiered', avg_down=True)
    return _create_resnet('resnet50t', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet101d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet101d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet152d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 8, 36, 3), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet152d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet200(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 24, 36, 3))
    return _create_resnet('resnet200', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet200d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 24, 36, 3), stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnet200d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext50d_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), cardinality=32, base_width=4,
        stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnext50d_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext101_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=4)
    return _create_resnet('resnext101_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext101_32x8d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=8)
    return _create_resnet('resnext101_32x8d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext101_32x16d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=16)
    return _create_resnet('resnext101_32x16d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnext101_64x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=64, base_width=4)
    return _create_resnet('resnext101_64x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def wide_resnet101_2(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), base_width=128)
    return _create_resnet('wide_resnet101_2', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet34(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(3, 4, 6, 3), se_layer=SEModule)
    return _create_resnet('seresnet34', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet50t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep_tiered',
        avg_down=True, se_layer=SEModule)
    return _create_resnet('seresnet50t', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet101(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), se_layer=SEModule)
    return _create_resnet('seresnet101', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet152(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 8, 36, 3), se_layer=SEModule)
    return _create_resnet('seresnet152', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext26d_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(2, 2, 2, 2), cardinality=32, base_width=4, stem_width=32,
        stem_type='deep', avg_down=True, se_layer=SEModule)
    return _create_resnet('seresnext26d_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext26t_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(2, 2, 2, 2), cardinality=32, base_width=4, stem_width=32,
        stem_type='deep_tiered', avg_down=True, se_layer=SEModule)
    return _create_resnet('seresnext26t_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext50_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), cardinality=32, base_width=4, se_layer=SEModule)
    return _create_resnet('seresnext50_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext101_32x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=4, se_layer=SEModule)
    return _create_resnet('seresnext101_32x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext101_32x8d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=8, se_layer=SEModule)
    return _create_resnet('seresnext101_32x8d', pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext101_64x4d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=64, base_width=4, se_layer=SEModule)
    return _create_resnet('seresnext101_64x4d', pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet26t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(2, 2, 2, 2), stem_width=32, stem_type='deep_tiered',
        avg_down=True, se_layer=EcaModule)
    return _create_resnet('ecaresnet26t', pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet50d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep',
        avg_down=True, se_layer=EcaModule)
    return _create_resnet('ecaresnet50d', pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet50t(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep_tiered',
        avg_down=True, se_layer=EcaModule)
    return _create_resnet('ecaresnet50t', pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet101d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), stem_width=32, stem_type='deep',
        avg_down=True, se_layer=EcaModule)
    return _create_resnet('ecaresnet101d', pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnetlight(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(1, 1, 11, 3), stem_width=32, avg_down=True, se_layer=EcaModule)
    return _create_resnet('ecaresnetlight', pretrained, **dict(model_args, **kwargs))


@register_model
def test_resnet(pretrained=False, **kwargs) -> ResNet:
    """Tiny fixture (reference resnet.py:2213)."""
    model_args = dict(block=BasicBlock, layers=(1, 1, 1, 1), channels=(32, 48, 48, 96))
    return _create_resnet('test_resnet', pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50c(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50-C model."""
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep')
    return _create_resnet('resnet50c', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50s(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50-S model."""
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), stem_width=64, stem_type='deep')
    return _create_resnet('resnet50s', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet101c(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-101-C model."""
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), stem_width=32, stem_type='deep')
    return _create_resnet('resnet101c', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet101s(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-101-S model."""
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), stem_width=64, stem_type='deep')
    return _create_resnet('resnet101s', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet152c(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-152-C model."""
    model_args = dict(block=Bottleneck, layers=(3, 8, 36, 3), stem_width=32, stem_type='deep')
    return _create_resnet('resnet152c', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet152s(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-152-S model."""
    model_args = dict(block=Bottleneck, layers=(3, 8, 36, 3), stem_width=64, stem_type='deep')
    return _create_resnet('resnet152s', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnet50_gn(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50 model w/ GroupNorm"""
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), norm_layer='groupnorm')
    return _create_resnet('resnet50_gn', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnext101_32x32d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNeXt-101 32x32d model"""
    model_args = dict(block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=32)
    return _create_resnet('resnext101_32x32d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet50d_pruned(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50-D model pruned with eca."""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep', avg_down=True,
        block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnet50d_pruned', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet101d_pruned(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-101-D model pruned with eca."""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), stem_width=32, stem_type='deep', avg_down=True,
        block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnet101d_pruned', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet200d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-200-D model with ECA."""
    model_args = dict(
        block=Bottleneck, layers=(3, 24, 36, 3), stem_width=32, stem_type='deep', avg_down=True,
        block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnet200d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnet269d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-269-D model with ECA."""
    model_args = dict(
        block=Bottleneck, layers=(3, 30, 48, 8), stem_width=32, stem_type='deep', avg_down=True,
        block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnet269d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnext26t_32x4d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs an ECA-ResNeXt-26-T model."""
    model_args = dict(
        block=Bottleneck, layers=(2, 2, 2, 2), cardinality=32, base_width=4, stem_width=32,
        stem_type='deep_tiered', avg_down=True, block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnext26t_32x4d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def ecaresnext50t_32x4d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs an ECA-ResNeXt-50-T model."""
    model_args = dict(
        block=Bottleneck, layers=(2, 2, 2, 2), cardinality=32, base_width=4, stem_width=32,
        stem_type='deep_tiered', avg_down=True, block_args=dict(attn_layer='eca'))
    return _create_resnet('ecaresnext50t_32x4d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet18(pretrained: bool = False, **kwargs) -> ResNet:
    model_args = dict(block=BasicBlock, layers=(2, 2, 2, 2), block_args=dict(attn_layer='se'))
    return _create_resnet('seresnet18', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet152d(pretrained: bool = False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 8, 36, 3), stem_width=32, stem_type='deep',
        avg_down=True, block_args=dict(attn_layer='se'))
    return _create_resnet('seresnet152d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet200d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-200-D model with SE attn."""
    model_args = dict(
        block=Bottleneck, layers=(3, 24, 36, 3), stem_width=32, stem_type='deep',
        avg_down=True, block_args=dict(attn_layer='se'))
    return _create_resnet('seresnet200d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnet269d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-269-D model with SE attn."""
    model_args = dict(
        block=Bottleneck, layers=(3, 30, 48, 8), stem_width=32, stem_type='deep',
        avg_down=True, block_args=dict(attn_layer='se'))
    return _create_resnet('seresnet269d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnext101d_32x8d(pretrained: bool = False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=8,
        stem_width=32, stem_type='deep', avg_down=True,
        block_args=dict(attn_layer='se'))
    return _create_resnet('seresnext101d_32x8d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def senet154(pretrained: bool = False, **kwargs) -> ResNet:
    model_args = dict(
        block=Bottleneck, layers=(3, 8, 36, 3), cardinality=64, base_width=4, stem_type='deep',
        down_kernel_size=3, block_reduce_first=2, block_args=dict(attn_layer='se'))
    return _create_resnet('senet154', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetblur18(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-18 model with blur anti-aliasing"""
    model_args = dict(block=BasicBlock, layers=(2, 2, 2, 2), aa_layer=BlurPool2d)
    return _create_resnet('resnetblur18', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetblur50(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50 model with blur anti-aliasing"""
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), aa_layer=BlurPool2d)
    return _create_resnet('resnetblur50', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetblur50d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50-D model with blur anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), aa_layer=BlurPool2d,
        stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnetblur50d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetblur101d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-101-D model with blur anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), aa_layer=BlurPool2d,
        stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnetblur101d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetaa34d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-34-D model w/ avgpool anti-aliasing"""
    model_args = dict(
        block=BasicBlock, layers=(3, 4, 6, 3),  aa_layer=AvgPool2dAA, stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnetaa34d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetaa50(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50 model with avgpool anti-aliasing"""
    model_args = dict(block=Bottleneck, layers=(3, 4, 6, 3), aa_layer=AvgPool2dAA)
    return _create_resnet('resnetaa50', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetaa50d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-50-D model with avgpool anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), aa_layer=AvgPool2dAA,
        stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnetaa50d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetaa101d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-101-D model with avgpool anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), aa_layer=AvgPool2dAA,
        stem_width=32, stem_type='deep', avg_down=True)
    return _create_resnet('resnetaa101d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnetaa50d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a SE=ResNet-50-D model with avgpool anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), aa_layer=AvgPool2dAA,
        stem_width=32, stem_type='deep', avg_down=True, block_args=dict(attn_layer='se'))
    return _create_resnet('seresnetaa50d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnextaa101d_32x8d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a SE=ResNeXt-101-D 32x8d model with avgpool anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), cardinality=32, base_width=8,
        stem_width=32, stem_type='deep', avg_down=True, aa_layer=AvgPool2dAA,
        block_args=dict(attn_layer='se'))
    return _create_resnet('seresnextaa101d_32x8d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def seresnextaa201d_32x8d(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a SE=ResNeXt-101-D 32x8d model with avgpool anti-aliasing"""
    model_args = dict(
        block=Bottleneck, layers=(3, 24, 36, 4), cardinality=32, base_width=8,
        stem_width=64, stem_type='deep', avg_down=True, aa_layer=AvgPool2dAA,
        block_args=dict(attn_layer='se'))
    return _create_resnet('seresnextaa201d_32x8d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs50(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-50 model."""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs50', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs101(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-101 model."""
    model_args = dict(
        block=Bottleneck, layers=(3, 4, 23, 3), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs101', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs152(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-152 model."""
    model_args = dict(
        block=Bottleneck, layers=(3, 8, 36, 3), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs152', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs200(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-200 model."""
    model_args = dict(
        block=Bottleneck, layers=(3, 24, 36, 3), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs200', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs270(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-270 model."""
    model_args = dict(
        block=Bottleneck, layers=(4, 29, 53, 4), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs270', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs350(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-350 model."""
    model_args = dict(
        block=Bottleneck, layers=(4, 36, 72, 4), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs350', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetrs420(pretrained: bool = False, **kwargs) -> ResNet:
    """Constructs a ResNet-RS-420 model"""
    model_args = dict(
        block=Bottleneck, layers=(4, 44, 87, 4), stem_width=32, stem_type='deep', replace_stem_pool=True,
        avg_down=True,  block_args=dict(attn_layer=partial(get_attn('se'), rd_ratio=0.25)))
    return _create_resnet('resnetrs420', pretrained=pretrained, **dict(model_args, **kwargs))
