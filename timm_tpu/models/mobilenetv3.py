"""MobileNetV3 (reference: timm/models/mobilenetv3.py:1-1526), TPU-native NHWC.

Reuses the EfficientNet arch-string builder; differs in the efficient head
(pool → 1x1 conv → act → classifier) and hard-swish/hard-sigmoid gates.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, SelectAdaptivePool2d, SqueezeExcite, create_conv2d, get_act_fn
from ..layers.drop import Dropout
from ..layers.weight_init import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._efficientnet_builder import (
    EfficientNetBuilder, decode_arch_def, resolve_act_layer, resolve_bn_args, round_channels,
)
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['MobileNetV3']


class MobileNetV3(nnx.Module):
    def __init__(
            self,
            block_args: List[List[Dict]],
            num_classes: int = 1000,
            in_chans: int = 3,
            stem_size: int = 16,
            fix_stem: bool = False,
            num_features: int = 1280,
            head_bias: bool = True,
            head_norm: bool = False,
            pad_type: str = '',
            act_layer: Union[str, Callable] = 'hard_swish',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Union[str, Callable]] = None,
            se_layer: Callable = None,
            se_from_exp: bool = True,
            round_chs_fn: Callable = round_channels,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            layer_scale_init_value=None,
            global_pool: str = 'avg',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        se_layer = se_layer or partial(
            SqueezeExcite, gate_layer='hard_sigmoid', force_act_layer='relu',
            rd_round_fn=round_channels)

        if not fix_stem:
            stem_size = round_chs_fn(stem_size)
        self.conv_stem = create_conv2d(
            in_chans, stem_size, 3, stride=2, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(stem_size, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        builder = EfficientNetBuilder(
            output_stride=32,
            pad_type=pad_type,
            round_chs_fn=round_chs_fn,
            se_from_exp=se_from_exp,
            act_layer=act_layer,
            norm_layer=norm_layer,
            aa_layer=aa_layer,
            se_layer=se_layer,
            drop_path_rate=drop_path_rate,
            layer_scale_init_value=layer_scale_init_value,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.blocks = nnx.List(builder(stem_size, block_args))
        self.feature_info = builder.features
        head_chs = builder.in_chs

        # efficient head: pool first, then 1x1 conv expansion on (B,1,1,C)
        self.num_features = head_chs
        self.head_hidden_size = num_features
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        self.conv_head = create_conv2d(
            head_chs, num_features, 1, bias=head_bias, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm_head = norm_layer(num_features, act_layer=act_layer, dtype=dtype,
                                    param_dtype=param_dtype, rngs=rngs) if head_norm else None
        self.act2 = get_act_fn(act_layer) if not head_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.classifier = nnx.Linear(
            num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self.grad_checkpointing = False
        self._dtype = dtype
        self._param_dtype = param_dtype

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv_stem|bn1',
            blocks=[
                (r'^blocks\.(\d+)' if coarse else r'^blocks\.(\d+)\.(\d+)', None),
                (r'conv_head|norm_head', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Linear(
            self.head_hidden_size, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def forward_features(self, x):
        x = self.bn1(self.conv_stem(x))
        for stage in self.blocks:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        if x.ndim == 2:
            x = x[:, None, None, :]
        x = self.conv_head(x)
        if self.norm_head is not None:
            x = self.norm_head(x)
        if self.act2 is not None:
            x = self.act2(x)
        x = x.reshape(x.shape[0], -1)
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x
        return self.classifier(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        x = self.bn1(self.conv_stem(x))
        intermediates = []
        stages = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, stage in enumerate(stages):
            for b in stage:
                x = b(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _create_mnv3(variant, pretrained=False, arch_def=None, **model_kwargs):
    from .efficientnet import checkpoint_filter_fn as _eff_filter
    n_stages = len(arch_def) if arch_def is not None else len(model_kwargs.get('block_args', ()))
    return build_model_with_cfg(
        MobileNetV3, variant, pretrained,
        pretrained_filter_fn=_eff_filter,
        feature_cfg=dict(out_indices=tuple(range(n_stages))),
        **model_kwargs,
    )


def _gen_mobilenet_v3(variant: str, channel_multiplier: float = 1.0, depth_multiplier: float = 1.0, group_size=None, pretrained: bool = False, **kwargs):
    """MobileNet-V3 large/small (+ 'minimal' SE/hswish-free twins)
    (reference mobilenetv3.py:557-666)."""
    if 'small' in variant:
        num_features = 1024
        if 'minimal' in variant:
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['ds_r1_k3_s2_e1_c16'],
                ['ir_r1_k3_s2_e4.5_c24', 'ir_r1_k3_s1_e3.67_c24'],
                ['ir_r1_k3_s2_e4_c40', 'ir_r2_k3_s1_e6_c40'],
                ['ir_r2_k3_s1_e3_c48'],
                ['ir_r3_k3_s2_e6_c96'],
                ['cn_r1_k1_s1_c576'],
            ]
        else:
            act_layer = resolve_act_layer(kwargs, 'hard_swish')
            arch_def = [
                ['ds_r1_k3_s2_e1_c16_se0.25_nre'],
                ['ir_r1_k3_s2_e4.5_c24_nre', 'ir_r1_k3_s1_e3.67_c24_nre'],
                ['ir_r1_k5_s2_e4_c40_se0.25', 'ir_r2_k5_s1_e6_c40_se0.25'],
                ['ir_r2_k5_s1_e3_c48_se0.25'],
                ['ir_r3_k5_s2_e6_c96_se0.25'],
                ['cn_r1_k1_s1_c576'],
            ]
    else:
        num_features = 1280
        if 'minimal' in variant:
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['ds_r1_k3_s1_e1_c16'],
                ['ir_r1_k3_s2_e4_c24', 'ir_r1_k3_s1_e3_c24'],
                ['ir_r3_k3_s2_e3_c40'],
                ['ir_r1_k3_s2_e6_c80', 'ir_r1_k3_s1_e2.5_c80', 'ir_r2_k3_s1_e2.3_c80'],
                ['ir_r2_k3_s1_e6_c112'],
                ['ir_r3_k3_s2_e6_c160'],
                ['cn_r1_k1_s1_c960'],
            ]
        else:
            act_layer = resolve_act_layer(kwargs, 'hard_swish')
            arch_def = [
                ['ds_r1_k3_s1_e1_c16_nre'],
                ['ir_r1_k3_s2_e4_c24_nre', 'ir_r1_k3_s1_e3_c24_nre'],
                ['ir_r3_k5_s2_e3_c40_se0.25_nre'],
                ['ir_r1_k3_s2_e6_c80', 'ir_r1_k3_s1_e2.5_c80', 'ir_r2_k3_s1_e2.3_c80'],
                ['ir_r2_k3_s1_e6_c112_se0.25'],
                ['ir_r3_k5_s2_e6_c160_se0.25'],
                ['cn_r1_k1_s1_c960'],
            ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier=depth_multiplier, group_size=group_size),
        num_features=num_features,
        stem_size=16,
        fix_stem=channel_multiplier < 0.75,
        round_chs_fn=round_chs_fn,
        norm_layer=partial(BatchNormAct2d, **resolve_bn_args(kwargs)),
        act_layer=act_layer,
        **kwargs,
    )
    return _create_mnv3(variant, pretrained, arch_def=arch_def, **model_kwargs)


def _gen_mobilenet_v3_rw(variant: str, channel_multiplier: float = 1.0, pretrained: bool = False, **kwargs):
    """timm's original MobileNet-V3 port (no force-relu SE, no head bias)
    (reference mobilenetv3.py:511-554)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_nre_noskip'],
        ['ir_r1_k3_s2_e4_c24_nre', 'ir_r1_k3_s1_e3_c24_nre'],
        ['ir_r3_k5_s2_e3_c40_se0.25_nre'],
        ['ir_r1_k3_s2_e6_c80', 'ir_r1_k3_s1_e2.5_c80', 'ir_r2_k3_s1_e2.3_c80'],
        ['ir_r2_k3_s1_e6_c112_se0.25'],
        ['ir_r3_k5_s2_e6_c160_se0.25'],
        ['cn_r1_k1_s1_c960'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        head_bias=False,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        norm_layer=partial(BatchNormAct2d, **resolve_bn_args(kwargs)),
        act_layer=resolve_act_layer(kwargs, 'hard_swish'),
        se_layer=partial(SqueezeExcite, gate_layer='hard_sigmoid'),
        **kwargs,
    )
    return _create_mnv3(variant, pretrained, arch_def=arch_def, **model_kwargs)


def _gen_fbnetv3(variant: str, channel_multiplier: float = 1.0, pretrained: bool = False, **kwargs):
    """FBNetV3 b/d/g (reference mobilenetv3.py:669-737)."""
    vl = variant.split('_')[-1]
    if vl in ('a', 'b'):
        stem_size = 16
        arch_def = [
            ['ds_r2_k3_s1_e1_c16'],
            ['ir_r1_k5_s2_e4_c24', 'ir_r3_k5_s1_e2_c24'],
            ['ir_r1_k5_s2_e5_c40_se0.25', 'ir_r4_k5_s1_e3_c40_se0.25'],
            ['ir_r1_k5_s2_e5_c72', 'ir_r4_k3_s1_e3_c72'],
            ['ir_r1_k3_s1_e5_c120_se0.25', 'ir_r5_k5_s1_e3_c120_se0.25'],
            ['ir_r1_k3_s2_e6_c184_se0.25', 'ir_r5_k5_s1_e4_c184_se0.25', 'ir_r1_k5_s1_e6_c224_se0.25'],
            ['cn_r1_k1_s1_c1344'],
        ]
    elif vl == 'd':
        stem_size = 24
        arch_def = [
            ['ds_r2_k3_s1_e1_c16'],
            ['ir_r1_k3_s2_e5_c24', 'ir_r5_k3_s1_e2_c24'],
            ['ir_r1_k5_s2_e4_c40_se0.25', 'ir_r4_k3_s1_e3_c40_se0.25'],
            ['ir_r1_k3_s2_e5_c72', 'ir_r4_k3_s1_e3_c72'],
            ['ir_r1_k3_s1_e5_c128_se0.25', 'ir_r6_k5_s1_e3_c128_se0.25'],
            ['ir_r1_k3_s2_e6_c208_se0.25', 'ir_r5_k5_s1_e5_c208_se0.25', 'ir_r1_k5_s1_e6_c240_se0.25'],
            ['cn_r1_k1_s1_c1440'],
        ]
    else:  # 'g'
        stem_size = 32
        arch_def = [
            ['ds_r3_k3_s1_e1_c24'],
            ['ir_r1_k5_s2_e4_c40', 'ir_r4_k5_s1_e2_c40'],
            ['ir_r1_k5_s2_e4_c56_se0.25', 'ir_r4_k5_s1_e3_c56_se0.25'],
            ['ir_r1_k5_s2_e5_c104', 'ir_r4_k3_s1_e3_c104'],
            ['ir_r1_k3_s1_e5_c160_se0.25', 'ir_r8_k5_s1_e3_c160_se0.25'],
            ['ir_r1_k3_s2_e6_c264_se0.25', 'ir_r6_k5_s1_e5_c264_se0.25', 'ir_r2_k5_s1_e6_c288_se0.25'],
            ['cn_r1_k1_s1_c1728'],
        ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier, round_limit=0.95)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1984,
        head_bias=False,
        stem_size=stem_size,
        round_chs_fn=round_chs_fn,
        se_from_exp=False,
        norm_layer=partial(BatchNormAct2d, **resolve_bn_args(kwargs)),
        act_layer=resolve_act_layer(kwargs, 'hard_swish'),
        se_layer=partial(SqueezeExcite, gate_layer='hard_sigmoid', rd_round_fn=round_chs_fn),
        **kwargs,
    )
    return _create_mnv3(variant, pretrained, arch_def=arch_def, **model_kwargs)


def _gen_lcnet(variant: str, channel_multiplier: float = 1.0, pretrained: bool = False, **kwargs):
    """PP-LCNet (reference mobilenetv3.py:740-782)."""
    arch_def = [
        ['dsa_r1_k3_s1_c32'],
        ['dsa_r2_k3_s2_c64'],
        ['dsa_r2_k3_s2_c128'],
        ['dsa_r1_k3_s2_c256', 'dsa_r1_k5_s1_c256'],
        ['dsa_r4_k5_s1_c256'],
        ['dsa_r2_k5_s2_c512_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=16,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        norm_layer=partial(BatchNormAct2d, **resolve_bn_args(kwargs)),
        act_layer=resolve_act_layer(kwargs, 'hard_swish'),
        se_layer=partial(SqueezeExcite, gate_layer='hard_sigmoid', force_act_layer='relu'),
        num_features=1280,
        **kwargs,
    )
    return _create_mnv3(variant, pretrained, arch_def=arch_def, **model_kwargs)


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem', 'classifier': 'classifier',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'mobilenetv3_large_075.untrained': _cfg(),
    'mobilenetv3_large_100.ra_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv3_small_050.lamb_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv3_small_075.lamb_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv3_small_100.lamb_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv3_rw.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'tf_mobilenetv3_large_075.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'tf_mobilenetv3_large_100.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'tf_mobilenetv3_large_minimal_100.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'tf_mobilenetv3_small_075.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'tf_mobilenetv3_small_100.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'tf_mobilenetv3_small_minimal_100.in1k': _cfg(hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    'fbnetv3_b.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), test_input_size=(3, 256, 256),
                               crop_pct=0.95),
    'fbnetv3_d.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), test_input_size=(3, 256, 256),
                               crop_pct=0.95),
    'fbnetv3_g.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), test_input_size=(3, 288, 288),
                               crop_pct=0.95, pool_size=(8, 8)),
    'lcnet_035.untrained': _cfg(),
    'lcnet_050.ra2_in1k': _cfg(hf_hub_id='timm/'),
    'lcnet_075.ra2_in1k': _cfg(hf_hub_id='timm/'),
    'lcnet_100.ra2_in1k': _cfg(hf_hub_id='timm/'),
    'lcnet_150.untrained': _cfg(),
    'mobilenetv3_large_150d.ra4_e3600_r256_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_conv_small_035.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, interpolation='bicubic', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=0.95),
    'mobilenetv4_conv_small_050.e3000_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, interpolation='bicubic', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=0.95),
    'mobilenetv4_conv_small.e2400_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=0.95),
    'mobilenetv4_conv_small.e1200_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=0.95),
    'mobilenetv4_conv_small.e3600_r256_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_conv_medium.e500_r256_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_conv_medium.e500_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=1.0),
    'mobilenetv4_conv_medium.e250_r384_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_conv_medium.e180_r384_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_conv_medium.e180_ad_r384_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_conv_medium.e250_r384_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_conv_large.e600_r384_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 448, 448), test_crop_pct=1.0),
    'mobilenetv4_conv_large.e500_r256_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium.e200_r256_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium.ix_e550_r256_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium.ix_e550_r384_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 448, 448), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium.e500_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium.e200_r256_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'mobilenetv4_hybrid_large.ix_e600_r384_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 448, 448), test_crop_pct=1.0),
    'mobilenetv4_hybrid_large.e600_r384_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 448, 448), test_crop_pct=1.0),
    'mobilenetv4_conv_aa_medium.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_conv_blur_medium.e500_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=1.0),
    'mobilenetv4_conv_aa_large.e230_r448_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448), pool_size=(14, 14), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 544, 544), test_crop_pct=1.0),
    'mobilenetv4_conv_aa_large.e230_r384_in12k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 480, 480), test_crop_pct=1.0),
    'mobilenetv4_conv_aa_large.e600_r384_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 480, 480), test_crop_pct=1.0),
    'mobilenetv4_conv_aa_large.e230_r384_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 448, 448), test_crop_pct=1.0),
    'mobilenetv4_hybrid_medium_075.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'mobilenetv4_hybrid_large_075.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.95, interpolation='bicubic', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
})


@register_model
def mobilenetv3_large_075(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_mobilenet_v3('mobilenetv3_large_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv3_large_100(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_mobilenet_v3('mobilenetv3_large_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv3_small_050(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_mobilenet_v3('mobilenetv3_small_050', 0.5, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv3_small_075(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_mobilenet_v3('mobilenetv3_small_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv3_small_100(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_mobilenet_v3('mobilenetv3_small_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv3_rw(pretrained=False, **kwargs) -> MobileNetV3:
    # reference keeps TF-default BN eps for this port (mobilenetv3.py:1322)
    kwargs.setdefault('bn_eps', 1e-3)
    return _gen_mobilenet_v3_rw('mobilenetv3_rw', 1.0, pretrained, **kwargs)


@register_model
def tf_mobilenetv3_large_075(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_large_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def tf_mobilenetv3_large_100(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_large_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def tf_mobilenetv3_large_minimal_100(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_large_minimal_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def tf_mobilenetv3_small_075(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_small_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def tf_mobilenetv3_small_100(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_small_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def tf_mobilenetv3_small_minimal_100(pretrained=False, **kwargs) -> MobileNetV3:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_mobilenet_v3('tf_mobilenetv3_small_minimal_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def fbnetv3_b(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_fbnetv3('fbnetv3_b', pretrained=pretrained, **kwargs)


@register_model
def fbnetv3_d(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_fbnetv3('fbnetv3_d', pretrained=pretrained, **kwargs)


@register_model
def fbnetv3_g(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_fbnetv3('fbnetv3_g', pretrained=pretrained, **kwargs)


@register_model
def lcnet_035(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_lcnet('lcnet_035', 0.35, pretrained=pretrained, **kwargs)


@register_model
def lcnet_050(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_lcnet('lcnet_050', 0.5, pretrained=pretrained, **kwargs)


@register_model
def lcnet_075(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_lcnet('lcnet_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def lcnet_100(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_lcnet('lcnet_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def lcnet_150(pretrained=False, **kwargs) -> MobileNetV3:
    return _gen_lcnet('lcnet_150', 1.5, pretrained=pretrained, **kwargs)


from .efficientnet import checkpoint_filter_fn  # noqa: E402,F401


def _gen_mobilenet_v4(
        variant: str,
        channel_multiplier: float = 1.0,
        group_size=None,
        pretrained: bool = False,
        **kwargs,
) -> MobileNetV3:
    """MobileNet-V4 (reference mobilenetv3.py:785-1041): universal inverted
    bottleneck (uir) stages, with multi-query mobile attention (mqa) blocks in
    the hybrid variants."""
    num_features = 1280
    if 'hybrid' in variant:
        layer_scale_init_value = 1e-5
        if 'medium' in variant:
            stem_size = 32
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['er_r1_k3_s2_e4_c48'],
                ['uir_r1_a3_k5_s2_e4_c80', 'uir_r1_a3_k3_s1_e2_c80'],
                [
                    'uir_r1_a3_k5_s2_e6_c160',
                    'uir_r1_a0_k0_s1_e2_c160',
                    'uir_r1_a3_k3_s1_e4_c160',
                    'uir_r1_a3_k5_s1_e4_c160',
                    'mqa_r1_k3_h4_s1_v2_d64_c160',
                    'uir_r1_a3_k3_s1_e4_c160',
                    'mqa_r1_k3_h4_s1_v2_d64_c160',
                    'uir_r1_a3_k0_s1_e4_c160',
                    'mqa_r1_k3_h4_s1_v2_d64_c160',
                    'uir_r1_a3_k3_s1_e4_c160',
                    'mqa_r1_k3_h4_s1_v2_d64_c160',
                    'uir_r1_a3_k0_s1_e4_c160',
                ],
                [
                    'uir_r1_a5_k5_s2_e6_c256',
                    'uir_r1_a5_k5_s1_e4_c256',
                    'uir_r2_a3_k5_s1_e4_c256',
                    'uir_r1_a0_k0_s1_e2_c256',
                    'uir_r1_a3_k5_s1_e2_c256',
                    'uir_r1_a0_k0_s1_e2_c256',
                    'uir_r1_a0_k0_s1_e4_c256',
                    'mqa_r1_k3_h4_s1_d64_c256',
                    'uir_r1_a3_k0_s1_e4_c256',
                    'mqa_r1_k3_h4_s1_d64_c256',
                    'uir_r1_a5_k5_s1_e4_c256',
                    'mqa_r1_k3_h4_s1_d64_c256',
                    'uir_r1_a5_k0_s1_e4_c256',
                    'mqa_r1_k3_h4_s1_d64_c256',
                    'uir_r1_a5_k0_s1_e4_c256',
                ],
                ['cn_r1_k1_s1_c960'],
            ]
        elif 'large' in variant:
            stem_size = 24
            act_layer = resolve_act_layer(kwargs, 'gelu')
            arch_def = [
                ['er_r1_k3_s2_e4_c48'],
                ['uir_r1_a3_k5_s2_e4_c96', 'uir_r1_a3_k3_s1_e4_c96'],
                [
                    'uir_r1_a3_k5_s2_e4_c192',
                    'uir_r3_a3_k3_s1_e4_c192',
                    'uir_r1_a3_k5_s1_e4_c192',
                    'uir_r2_a5_k3_s1_e4_c192',
                    'mqa_r1_k3_h8_s1_v2_d48_c192',
                    'uir_r1_a5_k3_s1_e4_c192',
                    'mqa_r1_k3_h8_s1_v2_d48_c192',
                    'uir_r1_a5_k3_s1_e4_c192',
                    'mqa_r1_k3_h8_s1_v2_d48_c192',
                    'uir_r1_a5_k3_s1_e4_c192',
                    'mqa_r1_k3_h8_s1_v2_d48_c192',
                    'uir_r1_a3_k0_s1_e4_c192',
                ],
                [
                    'uir_r4_a5_k5_s2_e4_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                    'uir_r1_a5_k3_s1_e4_c512',
                    'uir_r2_a5_k0_s1_e4_c512',
                    'uir_r1_a5_k3_s1_e4_c512',
                    'uir_r1_a5_k5_s1_e4_c512',
                    'mqa_r1_k3_h8_s1_d64_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                    'mqa_r1_k3_h8_s1_d64_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                    'mqa_r1_k3_h8_s1_d64_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                    'mqa_r1_k3_h8_s1_d64_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                ],
                ['cn_r1_k1_s1_c960'],
            ]
        else:
            raise AssertionError(f'Unknown variant {variant}.')
    else:
        layer_scale_init_value = None
        if 'small' in variant:
            stem_size = 32
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['cn_r1_k3_s2_e1_c32', 'cn_r1_k1_s1_e1_c32'],
                ['cn_r1_k3_s2_e1_c96', 'cn_r1_k1_s1_e1_c64'],
                [
                    'uir_r1_a5_k5_s2_e3_c96',
                    'uir_r4_a0_k3_s1_e2_c96',
                    'uir_r1_a3_k0_s1_e4_c96',
                ],
                [
                    'uir_r1_a3_k3_s2_e6_c128',
                    'uir_r1_a5_k5_s1_e4_c128',
                    'uir_r1_a0_k5_s1_e4_c128',
                    'uir_r1_a0_k5_s1_e3_c128',
                    'uir_r2_a0_k3_s1_e4_c128',
                ],
                ['cn_r1_k1_s1_c960'],
            ]
        elif 'medium' in variant:
            stem_size = 32
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['er_r1_k3_s2_e4_c48'],
                ['uir_r1_a3_k5_s2_e4_c80', 'uir_r1_a3_k3_s1_e2_c80'],
                [
                    'uir_r1_a3_k5_s2_e6_c160',
                    'uir_r2_a3_k3_s1_e4_c160',
                    'uir_r1_a3_k5_s1_e4_c160',
                    'uir_r1_a3_k3_s1_e4_c160',
                    'uir_r1_a3_k0_s1_e4_c160',
                    'uir_r1_a0_k0_s1_e2_c160',
                    'uir_r1_a3_k0_s1_e4_c160',
                ],
                [
                    'uir_r1_a5_k5_s2_e6_c256',
                    'uir_r1_a5_k5_s1_e4_c256',
                    'uir_r2_a3_k5_s1_e4_c256',
                    'uir_r1_a0_k0_s1_e4_c256',
                    'uir_r1_a3_k0_s1_e4_c256',
                    'uir_r1_a3_k5_s1_e2_c256',
                    'uir_r1_a5_k5_s1_e4_c256',
                    'uir_r2_a0_k0_s1_e4_c256',
                    'uir_r1_a5_k0_s1_e2_c256',
                ],
                ['cn_r1_k1_s1_c960'],
            ]
        elif 'large' in variant:
            stem_size = 24
            act_layer = resolve_act_layer(kwargs, 'relu')
            arch_def = [
                ['er_r1_k3_s2_e4_c48'],
                ['uir_r1_a3_k5_s2_e4_c96', 'uir_r1_a3_k3_s1_e4_c96'],
                [
                    'uir_r1_a3_k5_s2_e4_c192',
                    'uir_r3_a3_k3_s1_e4_c192',
                    'uir_r1_a3_k5_s1_e4_c192',
                    'uir_r5_a5_k3_s1_e4_c192',
                    'uir_r1_a3_k0_s1_e4_c192',
                ],
                [
                    'uir_r4_a5_k5_s2_e4_c512',
                    'uir_r1_a5_k0_s1_e4_c512',
                    'uir_r1_a5_k3_s1_e4_c512',
                    'uir_r2_a5_k0_s1_e4_c512',
                    'uir_r1_a5_k3_s1_e4_c512',
                    'uir_r1_a5_k5_s1_e4_c512',
                    'uir_r3_a5_k0_s1_e4_c512',
                ],
                ['cn_r1_k1_s1_c960'],
            ]
        else:
            raise AssertionError(f'Unknown variant {variant}.')

    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, group_size=group_size),
        head_bias=False,
        head_norm=True,
        num_features=num_features,
        stem_size=stem_size,
        fix_stem=channel_multiplier < 1.0,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        act_layer=act_layer,
        layer_scale_init_value=layer_scale_init_value,
        **kwargs,
    )
    return _create_mnv3(variant, pretrained, **model_kwargs)


@register_model
def mobilenetv3_large_150d(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V3 """
    model = _gen_mobilenet_v3('mobilenetv3_large_150d', 1.5, depth_multiplier=1.2, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_small_035(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 """
    model = _gen_mobilenet_v4('mobilenetv4_conv_small_035', 0.35, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_small_050(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 """
    model = _gen_mobilenet_v4('mobilenetv4_conv_small_050', 0.50, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_small(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 """
    model = _gen_mobilenet_v4('mobilenetv4_conv_small', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_medium(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 """
    model = _gen_mobilenet_v4('mobilenetv4_conv_medium', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_large(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 """
    model = _gen_mobilenet_v4('mobilenetv4_conv_large', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_hybrid_medium(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 Hybrid """
    model = _gen_mobilenet_v4('mobilenetv4_hybrid_medium', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_hybrid_large(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 Hybrid"""
    model = _gen_mobilenet_v4('mobilenetv4_hybrid_large', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_conv_aa_medium(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 w/ AvgPool AA """
    model = _gen_mobilenet_v4('mobilenetv4_conv_aa_medium', 1.0, pretrained=pretrained, aa_layer='avg', **kwargs)
    return model


@register_model
def mobilenetv4_conv_blur_medium(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 Conv w/ Blur AA """
    model = _gen_mobilenet_v4('mobilenetv4_conv_blur_medium', 1.0, pretrained=pretrained, aa_layer='blurpc', **kwargs)
    return model


@register_model
def mobilenetv4_conv_aa_large(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 w/ AvgPool AA """
    model = _gen_mobilenet_v4('mobilenetv4_conv_aa_large', 1.0, pretrained=pretrained, aa_layer='avg', **kwargs)
    return model


@register_model
def mobilenetv4_hybrid_medium_075(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 Hybrid """
    model = _gen_mobilenet_v4('mobilenetv4_hybrid_medium_075', 0.75, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv4_hybrid_large_075(pretrained: bool = False, **kwargs) -> MobileNetV3:
    """ MobileNet V4 Hybrid"""
    model = _gen_mobilenet_v4('mobilenetv4_hybrid_large_075', 0.75, pretrained=pretrained, **kwargs)
    return model
