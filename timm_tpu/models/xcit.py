"""XCiT: Cross-Covariance Image Transformer, TPU-native
(reference: timm/models/xcit.py:1-1085; El-Nouby et al. 2021).

Attention operates on the CHANNEL axis: the d×d cross-covariance of
l2-normalised q/k replaces the N×N token gram, so cost is linear in sequence
length. Each block adds a depthwise-conv Local Patch Interaction (LPI) for
spatial mixing, and classification runs CaiT-style class-attention layers on
top. TPU-first notes: XCA is two einsums over a (heads, d, d) core — tiny,
MXU-friendly matmuls at any resolution; the Fourier positional encoding is a
trace-time jnp computation (static H, W) feeding one 1×1 conv.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, DropPath, Dropout, LayerNorm, Mlp, to_2tuple, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .cait import ClassAttn

__all__ = ['Xcit', 'XCA', 'XCABlock']


class PositionalEncodingFourier(nnx.Module):
    """Fourier (sine/cosine) positional encoding w/ learned 1x1 projection
    (reference xcit.py:34-73)."""

    def __init__(self, hidden_dim: int = 32, dim: int = 768, temperature: float = 10000,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.token_projection = nnx.Conv(
            hidden_dim * 2, dim, kernel_size=(1, 1), dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.scale = 2 * math.pi
        self.temperature = temperature
        self.hidden_dim = hidden_dim
        self.dim = dim
        self.eps = 1e-6

    def __call__(self, H: int, W: int):
        # static H/W at trace time → whole grid is a constant-folded computation
        y = jnp.arange(1, H + 1, dtype=jnp.float32)[:, None]
        y = jnp.broadcast_to(y, (H, W))
        x = jnp.arange(1, W + 1, dtype=jnp.float32)[None, :]
        x = jnp.broadcast_to(x, (H, W))
        y = y / (y[-1:, :] + self.eps) * self.scale
        x = x / (x[:, -1:] + self.eps) * self.scale
        dim_t = jnp.arange(self.hidden_dim, dtype=jnp.float32)
        dim_t = self.temperature ** (2 * (dim_t // 2) / self.hidden_dim)
        pos_x = x[:, :, None] / dim_t
        pos_y = y[:, :, None] / dim_t
        pos_x = jnp.stack([jnp.sin(pos_x[:, :, 0::2]), jnp.cos(pos_x[:, :, 1::2])], axis=3).reshape(H, W, -1)
        pos_y = jnp.stack([jnp.sin(pos_y[:, :, 0::2]), jnp.cos(pos_y[:, :, 1::2])], axis=3).reshape(H, W, -1)
        pos = jnp.concatenate([pos_y, pos_x], axis=2)[None]  # (1, H, W, 2*hidden)
        return self.token_projection(pos)  # (1, H, W, dim)


class _ConvBn(nnx.Module):
    """3x3 stride-s conv + BN (reference xcit.py conv3x3)."""

    def __init__(self, in_chs: int, out_chs: int, stride: int = 1,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(3, 3), strides=stride, padding=[(1, 1), (1, 1)],
            use_bias=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_chs, rngs=rngs)

    def __call__(self, x):
        return self.bn(self.conv(x))


class ConvPatchEmbed(nnx.Module):
    """Multi-conv patch embedding (reference xcit.py:85-131)."""

    def __init__(self, img_size=224, patch_size: int = 16, in_chans: int = 3,
                 embed_dim: int = 768, act_layer: Union[str, Callable] = 'gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        from ..layers import get_act_fn
        img_size = to_2tuple(img_size)
        self.img_size = img_size
        self.patch_size = patch_size
        self.grid_size = (img_size[0] // patch_size, img_size[1] // patch_size)
        self.num_patches = self.grid_size[0] * self.grid_size[1]
        self.act = get_act_fn(act_layer)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if patch_size == 16:
            chs = [embed_dim // 8, embed_dim // 4, embed_dim // 2, embed_dim]
        elif patch_size == 8:
            chs = [embed_dim // 4, embed_dim // 2, embed_dim]
        else:
            raise ValueError('patch_size must be 8 or 16 for conv patch embed')
        stages = []
        in_c = in_chans
        for c in chs:
            stages.append(_ConvBn(in_c, c, stride=2, **kw))
            in_c = c
        self.stages = nnx.List(stages)

    def __call__(self, x):
        for i, stage in enumerate(self.stages):
            if i:
                x = self.act(x)
            x = stage(x)
        B, Hp, Wp, C = x.shape
        return x.reshape(B, Hp * Wp, C), (Hp, Wp)


class LPI(nnx.Module):
    """Local Patch Interaction: two depthwise 3x3 convs w/ BN
    (reference xcit.py:134-170)."""

    def __init__(self, in_features: int, act_layer: Union[str, Callable] = 'gelu',
                 kernel_size: int = 3, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        from ..layers import get_act_fn
        pad = kernel_size // 2
        self.conv1 = nnx.Conv(
            in_features, in_features, kernel_size=(kernel_size, kernel_size),
            padding=[(pad, pad), (pad, pad)], feature_group_count=in_features,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.bn = BatchNorm2d(in_features, rngs=rngs)
        self.conv2 = nnx.Conv(
            in_features, in_features, kernel_size=(kernel_size, kernel_size),
            padding=[(pad, pad), (pad, pad)], feature_group_count=in_features,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x, H: int, W: int):
        B, N, C = x.shape
        x = x.reshape(B, H, W, C)
        x = self.conv2(self.bn(self.act(self.conv1(x))))
        return x.reshape(B, N, C)


class XCA(nnx.Module):
    """Cross-covariance attention over channels (reference xcit.py:241-295)."""

    def __init__(self, dim: int, num_heads: int = 8, qkv_bias: bool = False,
                 attn_drop: float = 0.0, proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.temperature = nnx.Param(jnp.ones((num_heads, 1, 1), param_dtype))
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        d = C // self.num_heads
        # (B, h, d, N): channels are the attention axis
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, d).transpose(2, 0, 3, 4, 1)
        q, k, v = qkv[0], qkv[1], qkv[2]
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
        k = k / jnp.maximum(jnp.linalg.norm(k, axis=-1, keepdims=True), 1e-12)
        attn = jnp.einsum('bhdn,bhen->bhde', q, k) * self.temperature[...].astype(q.dtype)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        x = jnp.einsum('bhde,bhen->bhdn', attn, v)
        x = x.transpose(0, 3, 1, 2).reshape(B, N, C)
        x = self.proj(x)
        return self.proj_drop(x)

    def no_weight_decay(self):
        return {'temperature'}


class XCABlock(nnx.Module):
    """XCA + LPI + MLP block (reference xcit.py:297-351)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float = 4.0, qkv_bias: bool = False,
                 proj_drop: float = 0.0, attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 eta: float = 1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = XCA(dim, num_heads=num_heads, qkv_bias=qkv_bias,
                        attn_drop=attn_drop, proj_drop=proj_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm3 = norm_layer(dim, rngs=rngs)
        self.local_mp = LPI(dim, act_layer=act_layer, **kw)
        self.drop_path3 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                       drop=proj_drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)
        self.gamma1 = nnx.Param(jnp.full((dim,), eta, param_dtype))
        self.gamma3 = nnx.Param(jnp.full((dim,), eta, param_dtype))
        self.gamma2 = nnx.Param(jnp.full((dim,), eta, param_dtype))

    def __call__(self, x, H: int, W: int):
        x = x + self.drop_path1(self.gamma1[...].astype(x.dtype) * self.attn(self.norm1(x)))
        # reference applies 3 (LPI) before 2 (MLP) to match released weights
        x = x + self.drop_path3(self.gamma3[...].astype(x.dtype) * self.local_mp(self.norm3(x), H, W))
        x = x + self.drop_path2(self.gamma2[...].astype(x.dtype) * self.mlp(self.norm2(x)))
        return x


class ClassAttentionBlock(nnx.Module):
    """CaiT-style class-attention block w/ optional full-token norm
    (reference xcit.py:173-238)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float = 4.0, qkv_bias: bool = False,
                 proj_drop: float = 0.0, attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 eta: Optional[float] = 1.0, tokens_norm: bool = False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = ClassAttn(dim, num_heads=num_heads, qkv_bias=qkv_bias,
                              attn_drop=attn_drop, proj_drop=proj_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                       drop=proj_drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)
        if eta is not None:
            self.gamma1 = nnx.Param(jnp.full((dim,), eta, param_dtype))
            self.gamma2 = nnx.Param(jnp.full((dim,), eta, param_dtype))
        else:
            self.gamma1 = None
            self.gamma2 = None
        self.tokens_norm = tokens_norm

    def _g(self, gamma, y):
        return y if gamma is None else gamma[...].astype(y.dtype) * y

    def __call__(self, x):
        x_norm1 = self.norm1(x)
        x_attn = jnp.concatenate([self.attn(x_norm1), x_norm1[:, 1:]], axis=1)
        x = x + self.drop_path1(self._g(self.gamma1, x_attn))
        if self.tokens_norm:
            x = self.norm2(x)
        else:
            x = jnp.concatenate([self.norm2(x[:, 0:1]), x[:, 1:]], axis=1)
        x_res = x
        cls_token = self._g(self.gamma2, self.mlp(x[:, 0:1]))
        x = jnp.concatenate([cls_token, x[:, 1:]], axis=1)
        return x_res + self.drop_path2(x)


class Xcit(nnx.Module):
    """XCiT with the reference's full model contract (reference xcit.py:353-643)."""

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: int = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'token',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Callable] = None,
            cls_attn_layers: int = 2,
            use_pos_embed: bool = True,
            eta: float = 1.0,
            tokens_norm: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'avg', 'token')
        img_size = to_2tuple(img_size)
        assert img_size[0] % patch_size == 0 and img_size[1] % patch_size == 0
        norm_layer = norm_layer or partial(LayerNorm, eps=1e-6)
        self.num_classes = num_classes
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.global_pool = global_pool
        self.grad_checkpointing = False

        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.patch_embed = ConvPatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans,
            embed_dim=embed_dim, act_layer=act_layer, **kw)

        self.cls_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, 1, embed_dim), param_dtype))
        self.pos_embed = PositionalEncodingFourier(dim=embed_dim, **kw) if use_pos_embed else None
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        self.blocks = nnx.List([
            XCABlock(
                dim=embed_dim, num_heads=num_heads, mlp_ratio=mlp_ratio, qkv_bias=qkv_bias,
                proj_drop=proj_drop_rate, attn_drop=attn_drop_rate, drop_path=drop_path_rate,
                act_layer=act_layer, norm_layer=norm_layer, eta=eta, **kw)
            for _ in range(depth)
        ])
        self.feature_info = [
            dict(num_chs=embed_dim, reduction=patch_size, module=f'blocks.{i}') for i in range(depth)]

        self.cls_attn_blocks = nnx.List([
            ClassAttentionBlock(
                dim=embed_dim, num_heads=num_heads, mlp_ratio=mlp_ratio, qkv_bias=qkv_bias,
                proj_drop=drop_rate, attn_drop=attn_drop_rate, act_layer=act_layer,
                norm_layer=norm_layer, eta=eta, tokens_norm=tokens_norm, **kw)
            for _ in range(cls_attn_layers)
        ])

        self.norm = norm_layer(embed_dim, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'temperature'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed',
            blocks=r'^blocks\.(\d+)',
            cls_attn_blocks=[(r'^cls_attn_blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            assert global_pool in ('', 'avg', 'token')
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        B = x.shape[0]
        x, (Hp, Wp) = self.patch_embed(x)
        if self.pos_embed is not None:
            pos = self.pos_embed(Hp, Wp).reshape(1, -1, x.shape[-1])
            x = x + pos.astype(x.dtype)
        x = self.pos_drop(x)
        if self.grad_checkpointing:
            # remat per block; H/W are static python ints closed over safely
            remat_block = nnx.remat(lambda blk, x_, h, w: blk(x_, h, w), static_argnums=(2, 3))
            for blk in self.blocks:
                x = remat_block(blk, x, Hp, Wp)
        else:
            for blk in self.blocks:
                x = blk(x, Hp, Wp)
        cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        for blk in self.cls_attn_blocks:
            x = blk(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool:
            x = x[:, 1:].mean(axis=1) if self.global_pool == 'avg' else x[:, 0]
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B = x.shape[0]
        x, (Hp, Wp) = self.patch_embed(x)
        if self.pos_embed is not None:
            pos = self.pos_embed(Hp, Wp).reshape(1, -1, x.shape[-1])
            x = x + pos.astype(x.dtype)
        x = self.pos_drop(x)

        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x, Hp, Wp)
            if i in take_indices:
                intermediates.append(self.norm(x) if (norm and self.norm is not None) else x)
        if reshape:
            intermediates = [y.reshape(B, Hp, Wp, -1) for y in intermediates]
        if intermediates_only:
            return intermediates

        cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        for blk in self.cls_attn_blocks:
            x = blk(x)
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.cls_attn_blocks = nnx.List([])
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    import re
    if 'model' in state_dict:
        state_dict = state_dict['model']
    out = {}
    for k, v in state_dict.items():
        k = k.replace('pos_embeder.', 'pos_embed.')
        # torch nested Sequential (proj.{i}.{conv|bn}) → stages list (conv/bn named)
        m = re.match(r'^patch_embed\.proj\.(\d+)\.(\d+)\.(.*)$', k)
        if m:
            stage = int(m.group(1)) // 2
            part = 'conv' if m.group(2) == '0' else 'bn'
            k = f'patch_embed.stages.{stage}.{part}.{m.group(3)}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_xcit(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        Xcit, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 1.0,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.stages.0.conv',
        'classifier': 'head',
        'license': 'apache-2.0',
        **kwargs,
    }


_sizes = {
    'nano_12': dict(embed_dim=128, depth=12, num_heads=4),
    'tiny_12': dict(embed_dim=192, depth=12, num_heads=4),
    'small_12': dict(embed_dim=384, depth=12, num_heads=8),
    'tiny_24': dict(embed_dim=192, depth=24, num_heads=4),
    'small_24': dict(embed_dim=384, depth=24, num_heads=8),
    'medium_24': dict(embed_dim=512, depth=24, num_heads=8),
    'large_24': dict(embed_dim=768, depth=24, num_heads=16),
}

default_cfgs = generate_default_cfgs({
    **{f'xcit_{s}_p{p}_224.fb_in1k': _cfg(hf_hub_id='timm/')
       for s in _sizes for p in (16, 8)},
    **{f'xcit_{s}_p{p}_224.fb_dist_in1k': _cfg(hf_hub_id='timm/')
       for s in _sizes for p in (16, 8)},
    **{f'xcit_{s}_p{p}_384.fb_dist_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384))
       for s in _sizes for p in (16, 8)},
    'test_xcit.untrained': _cfg(input_size=(3, 96, 96)),
})


def _make_entrypoint(size_key: str, patch: int, res: int):
    args = _sizes[size_key]
    # nano uses eta=1.0 tokens_norm=False; 12-deep non-nano eta=1.0; 24-deep eta=1e-5
    eta = 1.0 if args['depth'] == 12 else 1e-5
    tokens_norm = not size_key.startswith('nano')
    name = f'xcit_{size_key}_p{patch}_{res}'

    def entrypoint(pretrained=False, **kwargs):
        model_args = dict(patch_size=patch, eta=eta, tokens_norm=tokens_norm, **args)
        if res != 224:
            model_args['img_size'] = res
        return _create_xcit(name, pretrained=pretrained, **dict(model_args, **kwargs))

    entrypoint.__name__ = name
    entrypoint.__doc__ = f'XCiT {size_key} p{patch} @{res} (reference xcit.py entrypoints)'
    return register_model(entrypoint)


for _s in _sizes:
    for _p in (16, 8):
        for _r in (224, 384):
            _make_entrypoint(_s, _p, _r)


@register_model
def test_xcit(pretrained=False, **kwargs) -> Xcit:
    model_args = dict(
        img_size=96, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        eta=1.0, tokens_norm=True, cls_attn_layers=1)
    return _create_xcit('test_xcit', pretrained=pretrained, **dict(model_args, **kwargs))
