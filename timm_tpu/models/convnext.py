"""ConvNeXt / ConvNeXt-V2, TPU-native NHWC.

Re-designed from the reference (timm/models/convnext.py:1-1437): blocks are
dwconv7x7 → LN → pointwise-MLP (Linear on channels-last) → LayerScale →
DropPath, all in NHWC so the MLP is a plain matmul on the MXU. V2 swaps
LayerScale for GRN in the MLP.

Contract parity: forward_features/forward_head, get/reset_classifier,
group_matcher, set_grad_checkpointing, forward_intermediates, feature_info.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    ClassifierHead, DropPath, GlobalResponseNormMlp, LayerNorm, LayerScale, Mlp,
    NormMlpClassifierHead, calculate_drop_path_rates, create_conv2d, get_act_fn,
    get_norm_layer, make_divisible, trunc_normal_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, resolve_stage_scan, scan_stage_stack,
    warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['ConvNeXt', 'ConvNeXtBlock']


class Downsample(nnx.Module):
    def __init__(self, in_chs, out_chs, stride=1, dilation=1, *, dtype=None, param_dtype=jnp.float32, rngs):
        if in_chs != out_chs or stride > 1:
            self.conv = create_conv2d(
                in_chs, out_chs, 1, stride=stride, dilation=dilation,
                bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.conv = None

    def __call__(self, x):
        if self.conv is None:
            return x
        return self.conv(x)


class ConvNeXtBlock(nnx.Module):
    """(reference convnext.py ConvNeXtBlock)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: Optional[int] = None,
            kernel_size: int = 7,
            stride: int = 1,
            dilation: int = 1,
            mlp_ratio: float = 4.0,
            conv_bias: bool = True,
            use_grn: bool = False,
            ls_init_value: Optional[float] = 1e-6,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Callable] = None,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        out_chs = out_chs or in_chs
        norm_layer = norm_layer or LayerNorm
        self.use_shortcut = stride == 1 and in_chs == out_chs

        self.conv_dw = create_conv2d(
            in_chs, out_chs, kernel_size, stride=stride, dilation=dilation,
            depthwise=True, bias=conv_bias, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(out_chs, rngs=rngs)
        mlp_layer = GlobalResponseNormMlp if use_grn else Mlp
        self.mlp = mlp_layer(
            out_chs, int(mlp_ratio * out_chs), act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.ls = LayerScale(out_chs, ls_init_value, param_dtype=param_dtype, rngs=rngs) \
            if ls_init_value is not None else None
        self.drop_path = DropPath(drop_path, rngs=rngs)
        self.shortcut = None if self.use_shortcut else Downsample(
            in_chs, out_chs, stride=stride, dilation=dilation,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        shortcut = x
        x = self.conv_dw(x)
        x = self.norm(x)
        x = self.mlp(x)
        if self.ls is not None:
            x = self.ls(x)
        x = self.drop_path(x)
        if self.shortcut is not None:
            shortcut = self.shortcut(shortcut)
        return x + shortcut


class ConvNeXtStage(nnx.Module):
    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            kernel_size: int = 7,
            stride: int = 2,
            depth: int = 2,
            dilation=(1, 1),
            drop_path_rates=None,
            ls_init_value: Optional[float] = 1.0,
            conv_bias: bool = True,
            use_grn: bool = False,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Callable] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        norm_layer = norm_layer or LayerNorm
        if in_chs != out_chs or stride > 1 or dilation[0] != dilation[1]:
            self.downsample_norm = norm_layer(in_chs, rngs=rngs)
            self.downsample_conv = create_conv2d(
                in_chs, out_chs, stride if stride > 1 else 1,
                stride=stride, dilation=dilation[0], padding=0, bias=conv_bias,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            in_chs = out_chs
        else:
            self.downsample_norm = None
            self.downsample_conv = None

        drop_path_rates = drop_path_rates or [0.0] * depth
        self.blocks = nnx.List([
            ConvNeXtBlock(
                in_chs=in_chs if i == 0 else out_chs,
                out_chs=out_chs,
                kernel_size=kernel_size,
                dilation=dilation[1],
                drop_path=drop_path_rates[i],
                ls_init_value=ls_init_value,
                conv_bias=conv_bias,
                use_grn=use_grn,
                act_layer=act_layer,
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        self.grad_checkpointing = False
        self.stage_scan = False

    def __call__(self, x):
        if self.downsample_norm is not None:
            x = self.downsample_norm(x)
            x = self.downsample_conv(x)
        if self.stage_scan:
            try:
                return scan_stage_stack(self.blocks, x, remat=self.grad_checkpointing)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e, what='stage_scan')
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class ConvNeXt(nnx.Module):
    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            output_stride: int = 32,
            depths: Tuple[int, ...] = (3, 3, 9, 3),
            dims: Tuple[int, ...] = (96, 192, 384, 768),
            kernel_sizes: Union[int, Tuple[int, ...]] = 7,
            ls_init_value: Optional[float] = 1e-6,
            stem_type: str = 'patch',
            patch_size: int = 4,
            head_init_scale: float = 1.0,
            head_norm_first: bool = False,
            head_hidden_size: Optional[int] = None,
            conv_bias: bool = True,
            use_grn: bool = False,
            conv_mlp: bool = False,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Optional[Union[str, Callable]] = None,
            norm_eps: Optional[float] = None,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride in (8, 16, 32)
        if isinstance(kernel_sizes, int):
            kernel_sizes = (kernel_sizes,) * 4
        # conv_mlp only changes the reference's torch memory layout (1x1-conv
        # MLP in NCHW vs Linear in NLC); in NHWC a Linear IS a 1x1 conv, so the
        # flag is accepted for cfg parity but structurally a no-op here.
        del conv_mlp
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        if norm_eps is not None:
            norm_layer = partial(norm_layer, eps=norm_eps)

        self.num_classes = num_classes
        self.drop_rate = drop_rate

        # stem
        assert stem_type in ('patch', 'overlap', 'overlap_tiered', 'overlap_act')
        if stem_type == 'patch':
            self.stem_conv = create_conv2d(
                in_chans, dims[0], patch_size, stride=patch_size, padding=0, bias=conv_bias,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.stem_conv2 = None
            self.stem_norm = norm_layer(dims[0], rngs=rngs)
            stem_stride = patch_size
        else:
            mid_chs = make_divisible(dims[0] // 2) if 'tiered' in stem_type else dims[0]
            self.stem_conv = create_conv2d(
                in_chans, mid_chs, 3, stride=2, padding=None, bias=conv_bias,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.stem_act = get_act_fn(act_layer) if 'act' in stem_type else None
            self.stem_conv2 = create_conv2d(
                mid_chs, dims[0], 3, stride=2, padding=None, bias=conv_bias,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.stem_norm = norm_layer(dims[0], rngs=rngs)
            stem_stride = 4

        # stages
        dp_rates = calculate_drop_path_rates(drop_path_rate, list(depths), stagewise=True)
        stages = []
        prev_chs = dims[0]
        curr_stride = stem_stride
        dilation = 1
        self.feature_info = []
        for i in range(len(depths)):
            stride = 2 if curr_stride == 2 or i > 0 else 1
            if curr_stride >= output_stride and stride > 1:
                dilation *= stride
                stride = 1
            curr_stride *= stride
            first_dilation = 1 if dilation in (1, 2) else 2
            out_chs = dims[i]
            stages.append(ConvNeXtStage(
                prev_chs,
                out_chs,
                kernel_size=kernel_sizes[i],
                stride=stride,
                dilation=(first_dilation, dilation),
                depth=depths[i],
                drop_path_rates=dp_rates[i],
                ls_init_value=ls_init_value,
                conv_bias=conv_bias,
                use_grn=use_grn,
                act_layer=act_layer,
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            ))
            prev_chs = out_chs
            self.feature_info += [dict(num_chs=prev_chs, reduction=curr_stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)
        self.set_stage_scan(resolve_stage_scan(stage_scan))

        self.num_features = self.head_hidden_size = prev_chs
        if head_norm_first:
            self.norm_pre = norm_layer(self.num_features, rngs=rngs)
            self.head = ClassifierHead(
                self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.norm_pre = None
            self.head = NormMlpClassifierHead(
                self.num_features, num_classes,
                hidden_size=head_hidden_size,
                pool_type=global_pool,
                drop_rate=drop_rate,
                norm_layer=norm_layer,
                act_layer='gelu',
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            if head_hidden_size:
                self.head_hidden_size = head_hidden_size
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem_',
            blocks=r'^stages\.(\d+)' if coarse else [
                (r'^stages\.(\d+)\.downsample', (0,)),
                (r'^stages\.(\d+)\.blocks\.(\d+)', None),
                (r'^norm_pre', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        for s in self.stages:
            s.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def _stem(self, x):
        x = self.stem_conv(x)
        if self.stem_conv2 is not None:
            if getattr(self, 'stem_act', None) is not None:
                x = self.stem_act(x)
            x = self.stem_conv2(x)
        return self.stem_norm(x)

    def forward_features(self, x):
        x = self._stem(x)
        for stage in self.stages:
            x = stage(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC', 'Conv models emit NHWC features'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self._stem(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_norm:
            self.norm_pre = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem_conv',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'convnext_atto.d2_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_femto.d1_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_pico.d1_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_nano.d1h_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_tiny.fb_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_small.fb_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_base.fb_in1k': _cfg(hf_hub_id='timm/'),
    'convnext_large.fb_in1k': _cfg(hf_hub_id='timm/'),
    'convnextv2_atto.fcmae_ft_in1k': _cfg(hf_hub_id='timm/'),
    'convnextv2_nano.fcmae_ft_in1k': _cfg(hf_hub_id='timm/'),
    'convnextv2_tiny.fcmae_ft_in1k': _cfg(hf_hub_id='timm/'),
    'convnextv2_base.fcmae_ft_in1k': _cfg(hf_hub_id='timm/'),
    'test_convnext.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_convnext2.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'test_convnext3.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'convnext_zepto_rms.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='stem.0', classifier='head.fc'),
    'convnext_zepto_rms_ols.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='stem.0', classifier='head.fc'),
    'convnext_atto_ols.a2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='stem.0', classifier='head.fc'),
    'convnext_atto_rms.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 256, 256), test_crop_pct=0.95, first_conv='stem.0', classifier='head.fc'),
    'convnext_femto_ols.d1_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='stem.0', classifier='head.fc'),
    'convnext_pico_ols.d1_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnext_nano_ols.d1h_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnext_tiny_hnf.a2h_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_soup_ft_in12k_in1k_320': _cfg(hf_hub_id='timm/', input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_soup_ft_in12k_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_augreg_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_augreg_ft_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_soup_ft_in12k_320': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_augreg_ft_in12k_384': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_soup_ft_in12k_384': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_augreg': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_ft_320': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_large_mlp.clip_laion2b_ft_soup_320': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_xlarge.fb_in22k_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnext_xlarge.fb_in22k_ft_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnext_xlarge.fb_in22k': _cfg(hf_hub_id='timm/', num_classes=21841, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnext_xxlarge.clip_laion2b_soup_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_xxlarge.clip_laion2b_soup_ft_in12k': _cfg(hf_hub_id='timm/', num_classes=11821, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_xxlarge.clip_laion2b_soup': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnext_xxlarge.clip_laion2b_rewind': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_femto.fcmae_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='stem.0', classifier='head.fc'),
    'convnextv2_femto.fcmae': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_pico.fcmae_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=0.95, first_conv='stem.0', classifier='head.fc'),
    'convnextv2_pico.fcmae': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_small.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_large.fcmae_ft_in22k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnextv2_large.fcmae_ft_in22k_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_large.fcmae_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnextv2_large.fcmae': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_huge.fcmae_ft_in22k_in1k_384': _cfg(hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_huge.fcmae_ft_in22k_in1k_512': _cfg(hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(15, 15), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
    'convnextv2_huge.fcmae_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), test_crop_pct=1.0, first_conv='stem.0', classifier='head.fc'),
    'convnextv2_huge.fcmae': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='stem.0', classifier='head.fc'),
})


def checkpoint_filter_fn(state_dict, model):
    """Map reference-timm convnext names → this module's layout
    (stem/downsample Sequential indices, bare `gamma` LayerScale)."""
    import re
    from ._torch_convert import convert_torch_state_dict
    import numpy as np
    # overlap stems: stem.0/stem.1 are convs (4D), stem.2 is the norm;
    # overlap_act stems have a paramless act at index 1 (conv at 2, norm at 3)
    overlap_act_stem = any(k.startswith('stem.3.') for k in state_dict)
    overlap_stem = any(k.startswith('stem.2.') for k in state_dict)
    out = {}
    for k, v in state_dict.items():
        if overlap_act_stem:
            k = re.sub(r'^stem\.0\.', 'stem_conv.', k)
            k = re.sub(r'^stem\.2\.', 'stem_conv2.', k)
            k = re.sub(r'^stem\.3\.', 'stem_norm.', k)
        elif overlap_stem:
            k = re.sub(r'^stem\.0\.', 'stem_conv.', k)
            k = re.sub(r'^stem\.1\.', 'stem_conv2.', k)
            k = re.sub(r'^stem\.2\.', 'stem_norm.', k)
        else:
            k = re.sub(r'^stem\.0\.', 'stem_conv.', k)
            k = re.sub(r'^stem\.1\.', 'stem_norm.', k)
        k = re.sub(r'(stages\.\d+)\.downsample\.0\.', r'\1.downsample_norm.', k)
        k = re.sub(r'(stages\.\d+)\.downsample\.1\.', r'\1.downsample_conv.', k)
        k = re.sub(r'(blocks\.\d+)\.gamma$', r'\1.ls.gamma', k)
        if k.endswith(('.grn.weight', '.grn.bias')):
            v = v.reshape(-1)  # reference stores (1,1,1,C)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_convnext(variant: str, pretrained: bool = False, **kwargs) -> ConvNeXt:
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        ConvNeXt,
        variant,
        pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def convnext_atto(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(40, 80, 160, 320), )
    return _create_convnext('convnext_atto', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_femto(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(48, 96, 192, 384), )
    return _create_convnext('convnext_femto', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_pico(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(64, 128, 256, 512), )
    return _create_convnext('convnext_pico', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_nano(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 8, 2), dims=(80, 160, 320, 640), )
    return _create_convnext('convnext_nano', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_tiny(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768))
    return _create_convnext('convnext_tiny', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_small(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 27, 3), dims=(96, 192, 384, 768))
    return _create_convnext('convnext_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_base(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024))
    return _create_convnext('convnext_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_large(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536))
    return _create_convnext('convnext_large', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_atto(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(40, 80, 160, 320), use_grn=True, ls_init_value=None, conv_bias=True)
    return _create_convnext('convnextv2_atto', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_nano(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 8, 2), dims=(80, 160, 320, 640), use_grn=True, ls_init_value=None, conv_bias=True)
    return _create_convnext('convnextv2_nano', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_tiny(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768), use_grn=True, ls_init_value=None)
    return _create_convnext('convnextv2_tiny', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_base(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024), use_grn=True, ls_init_value=None)
    return _create_convnext('convnextv2_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_convnext(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(1, 2, 4, 2), dims=(24, 32, 48, 64), norm_layer='layernorm')
    return _create_convnext('test_convnext', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_convnext2(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(1, 1, 1, 1), dims=(32, 64, 96, 128))
    return _create_convnext('test_convnext2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_convnext3(pretrained=False, **kwargs) -> ConvNeXt:
    model_args = dict(
        depths=(1, 1, 1, 1), dims=(32, 64, 96, 128), stem_type='overlap_tiered', use_grn=True, ls_init_value=None)
    return _create_convnext('test_convnext3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_zepto_rms(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 4, 2), dims=(32, 64, 128, 256), conv_mlp=True, norm_layer='simplenorm')
    return _create_convnext('convnext_zepto_rms', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_zepto_rms_ols(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(
        depths=(2, 2, 4, 2), dims=(32, 64, 128, 256), conv_mlp=True, norm_layer='simplenorm', stem_type='overlap_act')
    return _create_convnext('convnext_zepto_rms_ols', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_atto_ols(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(40, 80, 160, 320), conv_mlp=True, stem_type='overlap_tiered')
    return _create_convnext('convnext_atto_ols', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_atto_rms(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(40, 80, 160, 320), conv_mlp=True, norm_layer='rmsnorm2d')
    return _create_convnext('convnext_atto_rms', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_femto_ols(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(48, 96, 192, 384), conv_mlp=True, stem_type='overlap_tiered')
    return _create_convnext('convnext_femto_ols', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_pico_ols(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(64, 128, 256, 512), conv_mlp=True,  stem_type='overlap_tiered')
    return _create_convnext('convnext_pico_ols', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_nano_ols(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(2, 2, 8, 2), dims=(80, 160, 320, 640), conv_mlp=True, stem_type='overlap')
    return _create_convnext('convnext_nano_ols', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_tiny_hnf(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768), head_norm_first=True, conv_mlp=True)
    return _create_convnext('convnext_tiny_hnf', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_large_mlp(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 3, 27, 3], dims=[192, 384, 768, 1536], head_hidden_size=1536)
    return _create_convnext('convnext_large_mlp', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_xlarge(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 3, 27, 3], dims=[256, 512, 1024, 2048])
    return _create_convnext('convnext_xlarge', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnext_xxlarge(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 4, 30, 3], dims=[384, 768, 1536, 3072], norm_eps=kwargs.pop('norm_eps', 1e-5))
    return _create_convnext('convnext_xxlarge', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_femto(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(
        depths=(2, 2, 6, 2), dims=(48, 96, 192, 384), use_grn=True, ls_init_value=None, conv_mlp=True)
    return _create_convnext('convnextv2_femto', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_pico(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(
        depths=(2, 2, 6, 2), dims=(64, 128, 256, 512), use_grn=True, ls_init_value=None, conv_mlp=True)
    return _create_convnext('convnextv2_pico', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_small(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 3, 27, 3], dims=[96, 192, 384, 768], use_grn=True, ls_init_value=None)
    return _create_convnext('convnextv2_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_large(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 3, 27, 3], dims=[192, 384, 768, 1536], use_grn=True, ls_init_value=None)
    return _create_convnext('convnextv2_large', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def convnextv2_huge(pretrained: bool = False, **kwargs) -> ConvNeXt:
    model_args = dict(depths=[3, 3, 27, 3], dims=[352, 704, 1408, 2816], use_grn=True, ls_init_value=None)
    return _create_convnext('convnextv2_huge', pretrained=pretrained, **dict(model_args, **kwargs))
