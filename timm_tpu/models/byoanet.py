"""BYOANet: Bring-Your-Own-Attention networks, TPU-native
(reference: timm/models/byoanet.py:1-520).

ResNet-style trunks from the ByobNet meta-architecture with self-attention
spatial mixers — BoTNet (bottleneck attention), HaloNet (blocked local
attention w/ halo), LambdaNets (lambda layers) and hybrids. All attention
layers live in timm_tpu/layers/{bottleneck_attn,halo_attn,lambda_layer}.py
with trace-time-constant relative-position gathers.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .byobnet import ByoBlockCfg, ByoModelCfg, ByobNet, interleave_blocks

__all__ = []


model_cfgs = dict(
    botnet26t=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', fixed_input_size=True,
        self_attn_layer='bottleneck', self_attn_kwargs=dict(),
    ),
    sebotnet33ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=[2], d=3, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=[2], d=3, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg('self_attn', d=2, c=1536, s=2, gs=0, br=0.333),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='', act_layer='silu', num_features=1280,
        attn_layer='se', self_attn_layer='bottleneck', self_attn_kwargs=dict(),
    ),
    botnet50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=4, d=4, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=6, c=1024, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=3, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', act_layer='silu',
        fixed_input_size=True, self_attn_layer='bottleneck', self_attn_kwargs=dict(),
    ),
    eca_botnext26ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=16, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=16, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=16, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=16, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', fixed_input_size=True,
        act_layer='silu', attn_layer='eca',
        self_attn_layer='bottleneck', self_attn_kwargs=dict(dim_head=16),
    ),

    halonet_h1=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='self_attn', d=3, c=64, s=1, gs=0, br=1.0),
            ByoBlockCfg(type='self_attn', d=3, c=128, s=2, gs=0, br=1.0),
            ByoBlockCfg(type='self_attn', d=10, c=256, s=2, gs=0, br=1.0),
            ByoBlockCfg(type='self_attn', d=3, c=512, s=2, gs=0, br=1.0),
        ),
        stem_chs=64, stem_type='7x7', stem_pool='maxpool',
        self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=3),
    ),
    halonet26t=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool',
        self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=2),
    ),
    sehalonet33ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=[2], d=3, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=[2], d=3, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg('self_attn', d=2, c=1536, s=2, gs=0, br=0.333),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='', act_layer='silu', num_features=1280,
        attn_layer='se', self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=3),
    ),
    halonet50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(
                types=('bottle', 'self_attn'), every=4, d=4, c=512, s=2, gs=0, br=0.25,
                self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=3, num_heads=4)),
            interleave_blocks(types=('bottle', 'self_attn'), d=6, c=1024, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=3, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', act_layer='silu',
        self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=3),
    ),
    eca_halonext26ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=16, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=16, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=16, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=16, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', act_layer='silu', attn_layer='eca',
        self_attn_layer='halo', self_attn_kwargs=dict(block_size=8, halo_size=2, dim_head=16),
    ),

    lambda_resnet26t=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool',
        self_attn_layer='lambda', self_attn_kwargs=dict(r=9),
    ),
    lambda_resnet50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), every=4, d=4, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=6, c=1024, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=3, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', act_layer='silu',
        self_attn_layer='lambda', self_attn_kwargs=dict(r=9),
    ),
    lambda_resnet26rpt_256=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=256, s=1, gs=0, br=0.25),
            ByoBlockCfg(type='bottle', d=2, c=512, s=2, gs=0, br=0.25),
            interleave_blocks(types=('bottle', 'self_attn'), d=2, c=1024, s=2, gs=0, br=0.25),
            ByoBlockCfg(type='self_attn', d=2, c=2048, s=2, gs=0, br=0.25),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='maxpool', fixed_input_size=True,
        self_attn_layer='lambda', self_attn_kwargs=dict(r=None),
    ),

    haloregnetz_b=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=2, c=48, s=2, gs=16, br=3),
            ByoBlockCfg(type='bottle', d=6, c=96, s=2, gs=16, br=3),
            interleave_blocks(types=('bottle', 'self_attn'), every=3, d=12, c=192, s=2, gs=16, br=3),
            ByoBlockCfg('self_attn', d=2, c=288, s=2, gs=16, br=3),
        ),
        stem_chs=32, stem_pool='', downsample='', num_features=1536, act_layer='silu',
        attn_layer='se', attn_kwargs=dict(rd_ratio=0.25),
        block_kwargs=dict(bottle_in=True, linear_out=True),
        self_attn_layer='halo', self_attn_kwargs=dict(block_size=7, halo_size=2, qk_ratio=0.33),
    ),

    lamhalobotnet50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=4, c=512, s=2, gs=0, br=0.25,
                self_attn_layer='lambda', self_attn_kwargs=dict(r=13)),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=6, c=1024, s=2, gs=0, br=0.25,
                self_attn_layer='halo', self_attn_kwargs=dict(halo_size=3)),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=3, c=2048, s=2, gs=0, br=0.25,
                self_attn_layer='bottleneck', self_attn_kwargs=dict()),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='', act_layer='silu', fixed_input_size=True,
    ),
    halo2botnet50ts=ByoModelCfg(
        blocks=(
            ByoBlockCfg(type='bottle', d=3, c=256, s=1, gs=0, br=0.25),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=4, c=512, s=2, gs=0, br=0.25,
                self_attn_layer='halo', self_attn_kwargs=dict(halo_size=3)),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=6, c=1024, s=2, gs=0, br=0.25,
                self_attn_layer='halo', self_attn_kwargs=dict(halo_size=3)),
            interleave_blocks(
                types=('bottle', 'self_attn'), d=3, c=2048, s=2, gs=0, br=0.25,
                self_attn_layer='bottleneck', self_attn_kwargs=dict()),
        ),
        stem_chs=64, stem_type='tiered', stem_pool='', act_layer='silu', fixed_input_size=True,
    ),
)


def checkpoint_filter_fn(state_dict, model):
    """Lambda conv3d (K, 1, r, r, 1) → shared 2D conv HWIO (r, r, 1, K), then
    delegate to byobnet's filter."""
    import numpy as np
    from .byobnet import checkpoint_filter_fn as byob_filter
    out = {}
    for k, v in state_dict.items():
        v = np.asarray(v)
        if k.endswith('conv_lambda.weight') and v.ndim == 5:
            v = v[:, :, :, :, 0].transpose(2, 3, 1, 0)  # (r, r, 1, K)
            out[k[:-len('.weight')] + '.kernel'] = v
            continue
        out[k] = v
    return byob_filter(out, model)


def _create_byoanet(variant: str, cfg_variant: Optional[str] = None, pretrained: bool = False, **kwargs) -> ByobNet:
    return build_model_with_cfg(
        ByobNet, variant, pretrained,
        model_cfg=model_cfgs[variant] if not cfg_variant else model_cfgs[cfg_variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.95,
        'interpolation': 'bicubic',
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'stem.conv1.conv',
        'classifier': 'head.fc',
        'fixed_input_size': False,
        'min_input_size': (3, 224, 224),
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'botnet26t_256.c1_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
    'sebotnet33ts_256.a1h_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.94),
    'botnet50ts_256.untrained': _cfg(fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
    'eca_botnext26ts_256.c1_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
    'halonet_h1.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8), min_input_size=(3, 256, 256)),
    'halonet26t.a1h_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'sehalonet33ts.ra2_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.94),
    'halonet50ts.a1h_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8), crop_pct=0.94),
    'eca_halonext26ts.c1_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'lambda_resnet26t.c1_in1k': _cfg(
        hf_hub_id='timm/', min_input_size=(3, 128, 128), input_size=(3, 256, 256), pool_size=(8, 8)),
    'lambda_resnet50ts.a1h_in1k': _cfg(
        hf_hub_id='timm/', min_input_size=(3, 128, 128), input_size=(3, 256, 256), pool_size=(8, 8)),
    'lambda_resnet26rpt_256.c1_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
    'haloregnetz_b.ra3_in1k': _cfg(
        hf_hub_id='timm/', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
        first_conv='stem.conv', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.94),
    'lamhalobotnet50ts_256.a1h_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
    'halo2botnet50ts_256.a1h_in1k': _cfg(
        hf_hub_id='timm/', fixed_input_size=True, input_size=(3, 256, 256), pool_size=(8, 8)),
})


@register_model
def botnet26t_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('botnet26t_256', 'botnet26t', pretrained=pretrained, **kwargs)


@register_model
def sebotnet33ts_256(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('sebotnet33ts_256', 'sebotnet33ts', pretrained=pretrained, **kwargs)


@register_model
def botnet50ts_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('botnet50ts_256', 'botnet50ts', pretrained=pretrained, **kwargs)


@register_model
def eca_botnext26ts_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('eca_botnext26ts_256', 'eca_botnext26ts', pretrained=pretrained, **kwargs)


@register_model
def halonet_h1(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('halonet_h1', pretrained=pretrained, **kwargs)


@register_model
def halonet26t(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('halonet26t', pretrained=pretrained, **kwargs)


@register_model
def sehalonet33ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('sehalonet33ts', pretrained=pretrained, **kwargs)


@register_model
def halonet50ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('halonet50ts', pretrained=pretrained, **kwargs)


@register_model
def eca_halonext26ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('eca_halonext26ts', pretrained=pretrained, **kwargs)


@register_model
def lambda_resnet26t(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('lambda_resnet26t', pretrained=pretrained, **kwargs)


@register_model
def lambda_resnet50ts(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('lambda_resnet50ts', pretrained=pretrained, **kwargs)


@register_model
def lambda_resnet26rpt_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('lambda_resnet26rpt_256', 'lambda_resnet26rpt_256', pretrained=pretrained, **kwargs)


@register_model
def haloregnetz_b(pretrained=False, **kwargs) -> ByobNet:
    return _create_byoanet('haloregnetz_b', pretrained=pretrained, **kwargs)


@register_model
def lamhalobotnet50ts_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('lamhalobotnet50ts_256', 'lamhalobotnet50ts', pretrained=pretrained, **kwargs)


@register_model
def halo2botnet50ts_256(pretrained=False, **kwargs) -> ByobNet:
    kwargs.setdefault('img_size', 256)
    return _create_byoanet('halo2botnet50ts_256', 'halo2botnet50ts', pretrained=pretrained, **kwargs)
