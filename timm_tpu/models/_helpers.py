"""Model state (de)serialization helpers
(reference: timm/models/_helpers.py:1-261).

State dicts are flat `{dotted.path: np.ndarray}` mappings; the on-disk format
is safetensors (preferred) or .npz. Torch-checkpoint conversion lives in
`_torch_convert.py`.
"""
from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import nnx

_logger = logging.getLogger(__name__)

__all__ = [
    'clean_state_dict', 'model_state_dict', 'load_state_dict',
    'load_state_dict_into_model', 'save_state_dict', 'load_checkpoint',
    'remap_state_dict',
]


def clean_state_dict(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Strip wrapper prefixes (reference _helpers.py:79)."""
    cleaned = {}
    for k, v in state_dict.items():
        for prefix in ('module.', '_orig_mod.'):
            if k.startswith(prefix):
                k = k[len(prefix):]
        cleaned[k] = v
    return cleaned


def _path_str(path) -> str:
    return '.'.join(str(getattr(p, 'key', p)) for p in path)


def _is_buffer_key(key: str) -> bool:
    """Underscore-prefixed components mark non-persistent buffers (e.g. swin's
    _rel_index/_attn_mask constants) — excluded from the weight contract."""
    return any(part.startswith('_') for part in key.split('.'))


def model_state_dict(model: nnx.Module, include_stats: bool = True) -> Dict[str, np.ndarray]:
    """Flatten an nnx model's parameters (+ batch stats) to a flat dict."""
    state = nnx.state(model)
    out = {}
    for path, leaf in nnx.to_flat_state(state):
        value = leaf[...]
        if value is None:
            continue
        if not include_stats and not isinstance(leaf, nnx.Param):
            continue  # drop batch stats / other non-param variables
        key = _path_str(path)
        if 'rngs' in key or _is_buffer_key(key):
            continue  # rng streams / private buffers aren't weight content
        out[key] = np.asarray(value)
    return out


def load_state_dict_into_model(
        model: nnx.Module,
        state_dict: Dict[str, np.ndarray],
        strict: bool = True,
) -> nnx.Module:
    """Merge a flat dict back into model variables in-place."""
    state_dict = clean_state_dict(state_dict)
    state = nnx.state(model)
    flat = list(nnx.to_flat_state(state))
    used = set()
    missing = []
    for path, leaf in flat:
        key = _path_str(path)
        if 'rngs' in key or _is_buffer_key(key):
            continue
        if key in state_dict:
            new_val = jnp.asarray(state_dict[key])
            cur = leaf[...]
            if cur is not None and tuple(new_val.shape) != tuple(cur.shape):
                msg = f'Shape mismatch for {key}: ckpt {new_val.shape} vs model {cur.shape}'
                if strict:
                    raise ValueError(msg)
                _logger.warning(msg)
                continue
            if cur is not None:
                new_val = new_val.astype(cur.dtype)
            leaf[...] = new_val
            used.add(key)
        else:
            missing.append(key)
    unexpected = [k for k in state_dict if k not in used]
    if strict and (missing or unexpected):
        raise ValueError(f'State dict mismatch. Missing: {missing[:8]}..., Unexpected: {unexpected[:8]}...')
    if missing:
        _logger.warning(f'Missing keys: {missing[:8]}{"..." if len(missing) > 8 else ""}')
    if unexpected:
        _logger.warning(f'Unexpected keys: {unexpected[:8]}{"..." if len(unexpected) > 8 else ""}')
    nnx.update(model, state)
    return model


def save_state_dict(state_dict: Dict[str, np.ndarray], path: str):
    path = str(path)
    if path.endswith('.safetensors'):
        from safetensors.numpy import save_file
        save_file({k: np.ascontiguousarray(v) for k, v in state_dict.items()}, path)
    else:
        # durable write: tmp+fsync+replace with a hash manifest, same contract
        # as training checkpoints (resilience/durable.py)
        from ..resilience import atomic_write_npz
        atomic_write_npz(path, state_dict)


def load_state_dict(checkpoint_path: str, use_ema: bool = True) -> Dict[str, np.ndarray]:
    checkpoint_path = str(checkpoint_path)
    if not os.path.exists(checkpoint_path):
        raise FileNotFoundError(f'No checkpoint found at {checkpoint_path}')
    if checkpoint_path.endswith('.safetensors'):
        from safetensors.numpy import load_file
        sd = load_file(checkpoint_path)
    elif checkpoint_path.endswith(('.npz', '.npy')):
        # integrity gate (resilience/durable.py): hash-verified when a sidecar
        # manifest exists, zip-parse check otherwise — a truncated checkpoint
        # fails HERE with the reason instead of deep in np.load
        from ..resilience import CorruptCheckpointError, verify_checkpoint
        ok, reason = verify_checkpoint(checkpoint_path)
        if not ok:
            raise CorruptCheckpointError(f'{checkpoint_path}: {reason}')
        with np.load(checkpoint_path, allow_pickle=False) as data:
            sd = {k: data[k] for k in data.files}
    elif checkpoint_path.endswith(('.pth', '.pt', '.bin')):
        from ._torch_convert import load_torch_state_dict
        sd = load_torch_state_dict(checkpoint_path, use_ema=use_ema)
    else:
        raise ValueError(f'Unsupported checkpoint format: {checkpoint_path}')
    # unwrap EMA/nested containers saved by our CheckpointSaver; non-param
    # model variables (BN stats) live under 'model_state.' and are part of
    # the weight contract either way
    stats = {k[len('model_state.'):]: v for k, v in sd.items() if k.startswith('model_state.')}
    ema_keys = [k for k in sd if k.startswith('state_dict_ema.')]
    if use_ema and ema_keys:
        sd = {k[len('state_dict_ema.'):]: sd[k] for k in ema_keys}
        sd.update(stats)
    elif any(k.startswith('state_dict.') for k in sd):
        sd = {k[len('state_dict.'):]: v for k, v in sd.items() if k.startswith('state_dict.')}
        sd.update(stats)
    return clean_state_dict(sd)


def load_checkpoint(
        model: nnx.Module,
        checkpoint_path: str,
        use_ema: bool = True,
        strict: bool = True,
        remap: bool = False,
        filter_fn: Optional[Callable] = None,
):
    state_dict = load_state_dict(checkpoint_path, use_ema=use_ema)
    if remap:
        state_dict = remap_state_dict(state_dict, model)
    if filter_fn is not None:
        state_dict = filter_fn(state_dict, model)
    load_state_dict_into_model(model, state_dict, strict=strict)


def remap_state_dict(state_dict: Dict[str, np.ndarray], model: nnx.Module, allow_reshape: bool = True):
    """Remap by order when names differ but shapes align (reference _helpers.py:178)."""
    target = model_state_dict(model)
    out = {}
    for (ka, va), (kb, vb) in zip(target.items(), state_dict.items()):
        vb = np.asarray(vb)
        if va.size != vb.size:
            raise ValueError(f'Cannot remap {kb} ({vb.shape}) -> {ka} ({va.shape})')
        if va.shape != vb.shape:
            if not allow_reshape:
                raise ValueError(f'Shape mismatch remap {kb} -> {ka}')
            vb = vb.reshape(va.shape)
        out[ka] = vb
    return out
