"""DPN: Dual Path Networks, TPU-native NHWC
(reference: timm/models/dpn.py:1-400; Chen et al. 2017).

Blocks carry a (residual, dense) tuple; the dense path grows by `inc`
channels per block via concat — all static NHWC slices/concats.
"""
from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNormAct2d, ConvNormAct, Dropout, Pool2d, SelectAdaptivePool2d,
    create_conv2d, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['DPN']


class CatBnAct(nnx.Module):
    def __init__(self, in_chs, act_layer='relu', *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.bn = BatchNormAct2d(in_chs, eps=0.001, act_layer=act_layer,
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if isinstance(x, tuple):
            x = jnp.concatenate(x, axis=-1)
        return self.bn(x)


class BnActConv2d(nnx.Module):
    def __init__(self, in_chs, out_chs, kernel_size, stride, groups=1, act_layer='relu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.bn = BatchNormAct2d(in_chs, eps=0.001, act_layer=act_layer,
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv = create_conv2d(in_chs, out_chs, kernel_size, stride=stride, groups=groups,
                                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.conv(self.bn(x))


class DualPathBlock(nnx.Module):
    """(reference dpn.py:86-186)."""

    def __init__(self, in_chs, num_1x1_a, num_3x3_b, num_1x1_c, inc, groups,
                 block_type='normal', b=False, act_layer='relu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_1x1_c = num_1x1_c
        self.inc = inc
        self.b = b
        kw = dict(act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if block_type == 'proj':
            self.key_stride = 1
            has_proj = True
        elif block_type == 'down':
            self.key_stride = 2
            has_proj = True
        else:
            assert block_type == 'normal'
            self.key_stride = 1
            has_proj = False

        # distinct names for stride variants match the reference's checkpoint keys
        if has_proj and self.key_stride == 2:
            self.c1x1_w_s2 = BnActConv2d(in_chs, num_1x1_c + 2 * inc, 1, 2, **kw)
            self.c1x1_w_s1 = None
        elif has_proj:
            self.c1x1_w_s1 = BnActConv2d(in_chs, num_1x1_c + 2 * inc, 1, 1, **kw)
            self.c1x1_w_s2 = None
        else:
            self.c1x1_w_s1 = None
            self.c1x1_w_s2 = None

        self.c1x1_a = BnActConv2d(in_chs, num_1x1_a, 1, 1, **kw)
        self.c3x3_b = BnActConv2d(num_1x1_a, num_3x3_b, 3, self.key_stride, groups=groups, **kw)
        if b:
            self.c1x1_c = CatBnAct(num_3x3_b, act_layer=act_layer,
                                   dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.c1x1_c1 = create_conv2d(num_3x3_b, num_1x1_c, 1,
                                         dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.c1x1_c2 = create_conv2d(num_3x3_b, inc, 1,
                                         dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.c1x1_c = BnActConv2d(num_3x3_b, num_1x1_c + inc, 1, 1, **kw)
            self.c1x1_c1 = None
            self.c1x1_c2 = None

    def __call__(self, x):
        x_in = jnp.concatenate(x, axis=-1) if isinstance(x, tuple) else x
        if self.c1x1_w_s1 is None and self.c1x1_w_s2 is None:
            x_s1, x_s2 = x
        else:
            x_s = self.c1x1_w_s1(x_in) if self.c1x1_w_s1 is not None else self.c1x1_w_s2(x_in)
            x_s1 = x_s[..., :self.num_1x1_c]
            x_s2 = x_s[..., self.num_1x1_c:]
        y = self.c1x1_a(x_in)
        y = self.c3x3_b(y)
        y = self.c1x1_c(y)
        if self.c1x1_c1 is not None:
            out1 = self.c1x1_c1(y)
            out2 = self.c1x1_c2(y)
        else:
            out1 = y[..., :self.num_1x1_c]
            out2 = y[..., self.num_1x1_c:]
        resid = x_s1 + out1
        dense = jnp.concatenate([x_s2, out2], axis=-1)
        return resid, dense


class DPN(nnx.Module):
    """DPN with the reference's model contract (reference dpn.py:189-330)."""

    def __init__(
            self,
            k_sec: Tuple[int, ...] = (3, 4, 20, 3),
            inc_sec: Tuple[int, ...] = (16, 32, 24, 128),
            k_r: int = 96,
            groups: int = 32,
            num_classes: int = 1000,
            in_chans: int = 3,
            output_stride: int = 32,
            global_pool: str = 'avg',
            small: bool = False,
            num_init_features: int = 64,
            b: bool = False,
            drop_rate: float = 0.0,
            act_layer: str = 'relu',
            fc_act_layer: str = 'elu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.b = b
        self.grad_checkpointing = False
        kw = dict(act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        bw_factor = 1 if small else 4

        blocks = OrderedDict()
        blocks['conv1_1'] = ConvNormAct(
            in_chans, num_init_features, kernel_size=3 if small else 7, stride=2,
            norm_layer=partial(BatchNormAct2d, eps=0.001), act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.feature_info = [dict(num_chs=num_init_features, reduction=2, module='features.conv1_1')]

        in_chs = num_init_features
        for sec, (bw_mult, block_count, inc) in enumerate(zip((64, 128, 256, 512), k_sec, inc_sec)):
            bw = bw_mult * bw_factor
            r = (k_r * bw) // (64 * bw_factor)
            btype = 'proj' if sec == 0 else 'down'
            blocks[f'conv{sec + 2}_1'] = DualPathBlock(in_chs, r, r, bw, inc, groups, btype, b, **kw)
            in_chs = bw + 3 * inc
            for i in range(2, block_count + 1):
                blocks[f'conv{sec + 2}_{i}'] = DualPathBlock(
                    in_chs, r, r, bw, inc, groups, 'normal', b, **kw)
                in_chs += inc
            self.feature_info += [dict(
                num_chs=in_chs, reduction=4 * 2 ** sec, module=f'features.conv{sec + 2}_{block_count}')]
        # reference quirk preserved: fc_act_layer is silently dropped upstream
        # (get_norm_act_layer receives an already-act-bound partial), so the
        # final norm-act actually runs act_layer (relu) — verified empirically
        blocks['conv5_bn_ac'] = CatBnAct(in_chs, act_layer=act_layer,
                                         dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self._block_names = list(blocks.keys())
        for name, mod in blocks.items():
            setattr(self, f'features_{name}', mod)

        self.num_features = self.head_hidden_size = in_chs
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        # 1x1-conv classifier (reference uses conv fc for extra pooling schemes)
        self.classifier = nnx.Conv(
            in_chs, num_classes, kernel_size=(1, 1), use_bias=True,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^features_conv1', blocks=r'^features_conv(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Conv(
            self.num_features, num_classes, kernel_size=(1, 1), use_bias=True,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _run_blocks(self, x, collect=None, stop_at=None):
        pool = Pool2d('max', 3, 2, 1)
        intermediates = []
        for name in self._block_names:
            mod = getattr(self, f'features_{name}')
            x = mod(x)
            if collect is not None and name in collect:
                # stem feature is the PRE-pool conv output (reference collects
                # features.conv1_1, with conv1_pool a separate module)
                intermediates.append(jnp.concatenate(x, axis=-1) if isinstance(x, tuple) else x)
            if name == 'conv1_1':
                x = pool(x)
            if stop_at is not None and name == stop_at:
                break
        return x, intermediates

    def forward_features(self, x):
        x, _ = self._run_blocks(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        pooled = not self.global_pool.is_identity()
        x = self.global_pool(x)
        if x.ndim == 2:
            x = x[:, None, None, :]
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x.reshape(x.shape[0], -1) if pooled else x
        x = self.classifier(x)
        # conv classifier yields a spatial logit map when pooling is disabled
        return x.reshape(x.shape[0], -1) if pooled else x

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.feature_info), indices)
        collect = {self.feature_info[i]['module'].split('.')[-1] for i in take_indices}
        stop_at = self.feature_info[max_index]['module'].split('.')[-1] if stop_early else None
        x, intermediates = self._run_blocks(x, collect=collect, stop_at=stop_at)
        if intermediates_only:
            return intermediates
        if isinstance(x, tuple):
            x = jnp.concatenate(x, axis=-1)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.feature_info), indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        # torch Sequential(OrderedDict) 'features.convX_Y.*' → flat attrs
        if k.startswith('features.'):
            rest = k[len('features.'):]
            name, _, tail = rest.partition('.')
            k = f'features_{name}.{tail}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_dpn(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        DPN, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(feature_concat=True, flatten_sequential=True),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (124 / 255, 117 / 255, 104 / 255), 'std': (1 / (0.0167 * 255),) * 3,
        'first_conv': 'features_conv1_1.conv', 'classifier': 'classifier',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'dpn48b.untrained': _cfg(mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'dpn68.mx_in1k': _cfg(hf_hub_id='timm/'),
    'dpn68b.ra_in1k': _cfg(
        hf_hub_id='timm/', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
        crop_pct=0.95, test_input_size=(3, 288, 288), test_crop_pct=1.0),
    'dpn92.mx_in1k': _cfg(hf_hub_id='timm/'),
    'dpn98.mx_in1k': _cfg(hf_hub_id='timm/'),
    'dpn131.mx_in1k': _cfg(hf_hub_id='timm/'),
    'dpn107.mx_in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def dpn48b(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        small=True, num_init_features=10, k_r=128, groups=32,
        b=True, k_sec=(3, 4, 6, 3), inc_sec=(16, 32, 32, 64), act_layer='silu')
    return _create_dpn('dpn48b', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn68(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        small=True, num_init_features=10, k_r=128, groups=32,
        k_sec=(3, 4, 12, 3), inc_sec=(16, 32, 32, 64))
    return _create_dpn('dpn68', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn68b(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        small=True, num_init_features=10, k_r=128, groups=32,
        b=True, k_sec=(3, 4, 12, 3), inc_sec=(16, 32, 32, 64))
    return _create_dpn('dpn68b', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn92(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        num_init_features=64, k_r=96, groups=32,
        k_sec=(3, 4, 20, 3), inc_sec=(16, 32, 24, 128))
    return _create_dpn('dpn92', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn98(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        num_init_features=96, k_r=160, groups=40,
        k_sec=(3, 6, 20, 3), inc_sec=(16, 32, 32, 128))
    return _create_dpn('dpn98', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn131(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        num_init_features=128, k_r=160, groups=40,
        k_sec=(4, 8, 28, 3), inc_sec=(16, 32, 32, 128))
    return _create_dpn('dpn131', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def dpn107(pretrained=False, **kwargs) -> DPN:
    model_args = dict(
        num_init_features=128, k_r=200, groups=50,
        k_sec=(4, 8, 20, 3), inc_sec=(20, 64, 64, 128))
    return _create_dpn('dpn107', pretrained=pretrained, **dict(model_args, **kwargs))
