"""Inception-V3, TPU-native NHWC
(reference: timm/models/inception_v3.py:1-540; Szegedy et al. 2015).

Classic multi-branch conv trunk; branch concats are channel-last so XLA fuses
them into the following 1x1 projections.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import ConvNormAct, Dropout, SelectAdaptivePool2d, trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['InceptionV3']


def _max_pool3s2(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), 'VALID')


def _avg_pool3s1p1(x):
    # torch F.avg_pool2d(3, 1, 1) default count_include_pad=True
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    s = jax.lax.reduce_window(xp, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1), 'VALID')
    return s / 9.0


class InceptionA(nnx.Module):
    def __init__(self, in_channels, pool_features, conv_block, *, rngs):
        self.branch1x1 = conv_block(in_channels, 64, kernel_size=1, rngs=rngs)
        self.branch5x5_1 = conv_block(in_channels, 48, kernel_size=1, rngs=rngs)
        self.branch5x5_2 = conv_block(48, 64, kernel_size=5, padding=2, rngs=rngs)
        self.branch3x3dbl_1 = conv_block(in_channels, 64, kernel_size=1, rngs=rngs)
        self.branch3x3dbl_2 = conv_block(64, 96, kernel_size=3, padding=1, rngs=rngs)
        self.branch3x3dbl_3 = conv_block(96, 96, kernel_size=3, padding=1, rngs=rngs)
        self.branch_pool = conv_block(in_channels, pool_features, kernel_size=1, rngs=rngs)

    def __call__(self, x):
        b1 = self.branch1x1(x)
        b5 = self.branch5x5_2(self.branch5x5_1(x))
        b3 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = self.branch_pool(_avg_pool3s1p1(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nnx.Module):
    def __init__(self, in_channels, conv_block, *, rngs):
        self.branch3x3 = conv_block(in_channels, 384, kernel_size=3, stride=2, rngs=rngs)
        self.branch3x3dbl_1 = conv_block(in_channels, 64, kernel_size=1, rngs=rngs)
        self.branch3x3dbl_2 = conv_block(64, 96, kernel_size=3, padding=1, rngs=rngs)
        self.branch3x3dbl_3 = conv_block(96, 96, kernel_size=3, stride=2, rngs=rngs)

    def __call__(self, x):
        b3 = self.branch3x3(x)
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = _max_pool3s2(x)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nnx.Module):
    def __init__(self, in_channels, channels_7x7, conv_block, *, rngs):
        c7 = channels_7x7
        self.branch1x1 = conv_block(in_channels, 192, kernel_size=1, rngs=rngs)
        self.branch7x7_1 = conv_block(in_channels, c7, kernel_size=1, rngs=rngs)
        self.branch7x7_2 = conv_block(c7, c7, kernel_size=(1, 7), padding=(0, 3), rngs=rngs)
        self.branch7x7_3 = conv_block(c7, 192, kernel_size=(7, 1), padding=(3, 0), rngs=rngs)
        self.branch7x7dbl_1 = conv_block(in_channels, c7, kernel_size=1, rngs=rngs)
        self.branch7x7dbl_2 = conv_block(c7, c7, kernel_size=(7, 1), padding=(3, 0), rngs=rngs)
        self.branch7x7dbl_3 = conv_block(c7, c7, kernel_size=(1, 7), padding=(0, 3), rngs=rngs)
        self.branch7x7dbl_4 = conv_block(c7, c7, kernel_size=(7, 1), padding=(3, 0), rngs=rngs)
        self.branch7x7dbl_5 = conv_block(c7, 192, kernel_size=(1, 7), padding=(0, 3), rngs=rngs)
        self.branch_pool = conv_block(in_channels, 192, kernel_size=1, rngs=rngs)

    def __call__(self, x):
        b1 = self.branch1x1(x)
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_5(self.branch7x7dbl_4(self.branch7x7dbl_3(
            self.branch7x7dbl_2(self.branch7x7dbl_1(x)))))
        bp = self.branch_pool(_avg_pool3s1p1(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nnx.Module):
    def __init__(self, in_channels, conv_block, *, rngs):
        self.branch3x3_1 = conv_block(in_channels, 192, kernel_size=1, rngs=rngs)
        self.branch3x3_2 = conv_block(192, 320, kernel_size=3, stride=2, rngs=rngs)
        self.branch7x7x3_1 = conv_block(in_channels, 192, kernel_size=1, rngs=rngs)
        self.branch7x7x3_2 = conv_block(192, 192, kernel_size=(1, 7), padding=(0, 3), rngs=rngs)
        self.branch7x7x3_3 = conv_block(192, 192, kernel_size=(7, 1), padding=(3, 0), rngs=rngs)
        self.branch7x7x3_4 = conv_block(192, 192, kernel_size=3, stride=2, rngs=rngs)

    def __call__(self, x):
        b3 = self.branch3x3_2(self.branch3x3_1(x))
        b7 = self.branch7x7x3_4(self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x))))
        bp = _max_pool3s2(x)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nnx.Module):
    def __init__(self, in_channels, conv_block, *, rngs):
        self.branch1x1 = conv_block(in_channels, 320, kernel_size=1, rngs=rngs)
        self.branch3x3_1 = conv_block(in_channels, 384, kernel_size=1, rngs=rngs)
        self.branch3x3_2a = conv_block(384, 384, kernel_size=(1, 3), padding=(0, 1), rngs=rngs)
        self.branch3x3_2b = conv_block(384, 384, kernel_size=(3, 1), padding=(1, 0), rngs=rngs)
        self.branch3x3dbl_1 = conv_block(in_channels, 448, kernel_size=1, rngs=rngs)
        self.branch3x3dbl_2 = conv_block(448, 384, kernel_size=3, padding=1, rngs=rngs)
        self.branch3x3dbl_3a = conv_block(384, 384, kernel_size=(1, 3), padding=(0, 1), rngs=rngs)
        self.branch3x3dbl_3b = conv_block(384, 384, kernel_size=(3, 1), padding=(1, 0), rngs=rngs)
        self.branch_pool = conv_block(in_channels, 192, kernel_size=1, rngs=rngs)

    def __call__(self, x):
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = jnp.concatenate([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=-1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = jnp.concatenate([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], axis=-1)
        bp = self.branch_pool(_avg_pool3s1p1(x))
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nnx.Module):
    """Inception-V3 with the reference's model contract
    (reference inception_v3.py:284-470). Aux logits are a train-time-only
    artifact of the original recipe and are not implemented."""

    def __init__(
            self,
            num_classes: int = 1000,
            in_chans: int = 3,
            drop_rate: float = 0.0,
            global_pool: str = 'avg',
            aux_logits: bool = False,
            norm_eps: float = 1e-3,
            act_layer: str = 'relu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert not aux_logits, 'aux_logits head not implemented'
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        from ..layers import BatchNormAct2d
        conv_block = partial(
            ConvNormAct, padding=0, act_layer=act_layer,
            norm_layer=partial(BatchNormAct2d, eps=norm_eps),
            dtype=dtype, param_dtype=param_dtype)

        self.Conv2d_1a_3x3 = conv_block(in_chans, 32, kernel_size=3, stride=2, rngs=rngs)
        self.Conv2d_2a_3x3 = conv_block(32, 32, kernel_size=3, rngs=rngs)
        self.Conv2d_2b_3x3 = conv_block(32, 64, kernel_size=3, padding=1, rngs=rngs)
        self.Conv2d_3b_1x1 = conv_block(64, 80, kernel_size=1, rngs=rngs)
        self.Conv2d_4a_3x3 = conv_block(80, 192, kernel_size=3, rngs=rngs)
        self.Mixed_5b = InceptionA(192, 32, conv_block, rngs=rngs)
        self.Mixed_5c = InceptionA(256, 64, conv_block, rngs=rngs)
        self.Mixed_5d = InceptionA(288, 64, conv_block, rngs=rngs)
        self.Mixed_6a = InceptionB(288, conv_block, rngs=rngs)
        self.Mixed_6b = InceptionC(768, 128, conv_block, rngs=rngs)
        self.Mixed_6c = InceptionC(768, 160, conv_block, rngs=rngs)
        self.Mixed_6d = InceptionC(768, 160, conv_block, rngs=rngs)
        self.Mixed_6e = InceptionC(768, 192, conv_block, rngs=rngs)
        self.Mixed_7a = InceptionD(768, conv_block, rngs=rngs)
        self.Mixed_7b = InceptionE(1280, conv_block, rngs=rngs)
        self.Mixed_7c = InceptionE(2048, conv_block, rngs=rngs)
        self.feature_info = [
            dict(num_chs=64, reduction=2, module='Conv2d_2b_3x3'),
            dict(num_chs=192, reduction=4, module='Conv2d_4a_3x3'),
            dict(num_chs=288, reduction=8, module='Mixed_5d'),
            dict(num_chs=768, reduction=16, module='Mixed_6e'),
            dict(num_chs=2048, reduction=32, module='Mixed_7c'),
        ]

        self.num_features = self.head_hidden_size = 2048
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.fc = nnx.Linear(
            2048, num_classes, kernel_init=trunc_normal_(std=0.1), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^Conv2d_[12]', blocks=r'^Mixed_(\d)')

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        return self.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.fc = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.1),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _stages(self):
        return [
            lambda x: self.Conv2d_2b_3x3(self.Conv2d_2a_3x3(self.Conv2d_1a_3x3(x))),
            lambda x: self.Conv2d_4a_3x3(self.Conv2d_3b_1x1(_max_pool3s2(x))),
            lambda x: self.Mixed_5d(self.Mixed_5c(self.Mixed_5b(_max_pool3s2(x)))),
            lambda x: self.Mixed_6e(self.Mixed_6d(self.Mixed_6c(self.Mixed_6b(self.Mixed_6a(x))))),
            lambda x: self.Mixed_7c(self.Mixed_7b(self.Mixed_7a(x))),
        ]

    def forward_features(self, x):
        for stage in self._stages():
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        stages = self._stages()
        take_indices, max_index = feature_take_indices(len(stages), indices)
        intermediates = []
        for i, stage in enumerate(stages):
            if stop_early and i > max_index:
                break
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(5, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    out = {k: v for k, v in state_dict.items() if not k.startswith('AuxLogits')}
    return convert_torch_state_dict(out, model)


def _create_inception_v3(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        InceptionV3, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 299, 299), 'pool_size': (8, 8),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'Conv2d_1a_3x3.conv', 'classifier': 'fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'inception_v3.tv_in1k': _cfg(hf_hub_id='timm/'),
    'inception_v3.tf_in1k': _cfg(hf_hub_id='timm/'),
    'inception_v3.tf_adv_in1k': _cfg(hf_hub_id='timm/'),
    'inception_v3.gluon_in1k': _cfg(hf_hub_id='timm/', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
})


@register_model
def inception_v3(pretrained=False, **kwargs) -> InceptionV3:
    return _create_inception_v3('inception_v3', pretrained=pretrained, **kwargs)
