"""MaxxViT: CoAtNet + MaxViT meta-architecture, TPU-native
(reference: timm/models/maxxvit.py:1-2711; Tu et al. 'MaxViT', Dai et al.
'CoAtNet', plus timm 'rw' experimental variants).

One configurable trunk covers CoAtNet ('C'/'T' stages: MBConv + full-grid
transformer blocks), MaxViT ('M' blocks: MBConv → window attention → grid
attention), parallel-partition ('PM') and ConvNeXt-conv ('maxxvit') hybrids.

TPU-first notes: the reference maintains parallel NCHW (`Attention2d`,
`PartitionAttention2d`) and channels-last (`AttentionCl`) code paths purely
for torch memory-format performance; in NHWC/XLA there is one layout, so a
single attention/partition implementation serves every config (`use_nchw_attn`
is accepted and ignored). Window/grid partitions are reshape+transpose pairs
XLA folds into the attention matmuls; rel-pos bias tables gather with
trace-time constant indices (bias / mlp / tf-bias types).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    ClassifierHead, ConvMlp, DropPath, Dropout, LayerNorm, LayerScale,
    LayerScale2d, Mlp, NormMlpClassifierHead, RelPosBias, RelPosBiasTf,
    RelPosMlp, calculate_drop_path_rates, create_attn, create_conv2d,
    create_pool2d, extend_tuple, get_act_fn, get_norm_act_layer, get_norm_layer,
    make_divisible, to_2tuple, trunc_normal_tf_, zeros_,
)
from ..layers.attention import scaled_dot_product_attention
from ..layers.drop import dropout_rng_key
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model
from .swin_transformer import window_partition, window_reverse

__all__ = ['MaxxVit', 'MaxxVitCfg', 'MaxxVitConvCfg', 'MaxxVitTransformerCfg']


@dataclass
class MaxxVitTransformerCfg:
    """Field-compatible with reference maxxvit.py:85-116."""
    dim_head: int = 32
    head_first: bool = True
    expand_ratio: float = 4.0
    expand_first: bool = True
    shortcut_bias: bool = True
    attn_bias: bool = True
    attn_drop: float = 0.0
    proj_drop: float = 0.0
    pool_type: str = 'avg2'
    rel_pos_type: str = 'bias'
    rel_pos_dim: int = 512
    partition_ratio: int = 32
    window_size: Optional[Tuple[int, int]] = None
    grid_size: Optional[Tuple[int, int]] = None
    no_block_attn: bool = False
    use_nchw_attn: bool = False  # accepted for cfg parity; NHWC path is identical
    init_values: Optional[float] = None
    act_layer: str = 'gelu'
    norm_layer: str = 'layernorm2d'
    norm_layer_cl: str = 'layernorm'
    norm_eps: float = 1e-6

    def __post_init__(self):
        if self.grid_size is not None:
            self.grid_size = to_2tuple(self.grid_size)
        if self.window_size is not None:
            self.window_size = to_2tuple(self.window_size)
            if self.grid_size is None:
                self.grid_size = self.window_size


@dataclass
class MaxxVitConvCfg:
    """Field-compatible with reference maxxvit.py:119-153."""
    block_type: str = 'mbconv'
    expand_ratio: float = 4.0
    expand_output: bool = True
    kernel_size: int = 3
    group_size: int = 1
    pre_norm_act: bool = False
    output_bias: bool = True
    stride_mode: str = 'dw'
    pool_type: str = 'avg2'
    downsample_pool_type: str = 'avg2'
    padding: str = ''
    attn_early: bool = False
    attn_layer: str = 'se'
    attn_act_layer: str = 'silu'
    attn_ratio: float = 0.25
    init_values: Optional[float] = 1e-6
    act_layer: str = 'gelu'
    norm_layer: str = ''
    norm_layer_cl: str = ''
    norm_eps: Optional[float] = None

    def __post_init__(self):
        assert self.block_type in ('mbconv', 'convnext')
        use_mbconv = self.block_type == 'mbconv'
        if not self.norm_layer:
            self.norm_layer = 'batchnorm2d' if use_mbconv else 'layernorm2d'
        if not self.norm_layer_cl and not use_mbconv:
            self.norm_layer_cl = 'layernorm'
        if self.norm_eps is None:
            self.norm_eps = 1e-5 if use_mbconv else 1e-6
        self.downsample_pool_type = self.downsample_pool_type or self.pool_type


@dataclass
class MaxxVitCfg:
    """Field-compatible with reference maxxvit.py:156-166."""
    embed_dim: Tuple[int, ...] = (96, 192, 384, 768)
    depths: Tuple[int, ...] = (2, 3, 5, 2)
    block_type: Tuple[Union[str, Tuple[str, ...]], ...] = ('C', 'C', 'T', 'T')
    stem_width: Union[int, Tuple[int, int]] = 64
    stem_bias: bool = False
    conv_cfg: MaxxVitConvCfg = field(default_factory=MaxxVitConvCfg)
    transformer_cfg: MaxxVitTransformerCfg = field(default_factory=MaxxVitTransformerCfg)
    head_hidden_size: Optional[int] = None
    weight_init: str = 'vit_eff'


def grid_partition(x, grid_size: Tuple[int, int]):
    """(B, H, W, C) → (B*nW, gh*gw, C), dilated grid windows (reference
    maxxvit.py:762-771)."""
    B, H, W, C = x.shape
    gh, gw = grid_size
    x = x.reshape(B, gh, H // gh, gw, W // gw, C)
    return x.transpose(0, 2, 4, 1, 3, 5).reshape(-1, gh * gw, C)


def grid_reverse(windows, grid_size: Tuple[int, int], H: int, W: int):
    gh, gw = grid_size
    C = windows.shape[-1]
    x = windows.reshape(-1, H // gh, W // gw, gh, gw, C)
    return x.transpose(0, 3, 1, 4, 2, 5).reshape(-1, H, W, C)


def get_rel_pos_cls(cfg: MaxxVitTransformerCfg, window_size) -> Optional[Callable]:
    if cfg.rel_pos_type == 'mlp':
        return partial(RelPosMlp, window_size=window_size, hidden_dim=cfg.rel_pos_dim, mode='cr')
    if cfg.rel_pos_type == 'bias':
        return partial(RelPosBias, window_size=window_size)
    if cfg.rel_pos_type == 'bias_tf':
        return partial(RelPosBiasTf, window_size=window_size)
    return None


class MaxxAttention(nnx.Module):
    """Unified NHWC attention over flattened (B, N, C) tokens, serving both the
    reference's Attention2d (NCHW, 1x1-conv qkv) and AttentionCl (linear qkv)
    — identical math in channels-last (reference maxxvit.py:169-336)."""

    def __init__(
            self, dim: int, dim_out: Optional[int] = None, dim_head: int = 32,
            bias: bool = True, expand_first: bool = True, head_first: bool = True,
            rel_pos_cls: Optional[Callable] = None, attn_drop: float = 0.0, proj_drop: float = 0.0,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        dim_out = dim_out or dim
        dim_attn = dim_out if expand_first and dim_out > dim else dim
        assert dim_attn % dim_head == 0
        self.num_heads = dim_attn // dim_head
        self.dim_head = dim_head
        self.dim_attn = dim_attn
        self.head_first = head_first
        self.scale = dim_head ** -0.5

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim_attn * 3, use_bias=bias)
        self.rel_pos = rel_pos_cls(num_heads=self.num_heads, param_dtype=param_dtype, rngs=rngs) \
            if rel_pos_cls else None
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim_attn, dim_out, use_bias=bias)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, shared_rel_pos=None):
        B, N, C = x.shape
        qkv = self.qkv(x)
        if self.head_first:
            # channel layout (nh, 3, dh)
            qkv = qkv.reshape(B, N, self.num_heads, 3, self.dim_head)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            # channel layout (3, nh, dh)
            qkv = qkv.reshape(B, N, 3, self.num_heads, self.dim_head)
            q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)

        attn_bias = None
        if self.rel_pos is not None:
            attn_bias = self.rel_pos.get_bias()
        elif shared_rel_pos is not None:
            attn_bias = shared_rel_pos
        if attn_bias is not None:
            attn_bias = jnp.broadcast_to(
                attn_bias.astype(jnp.float32), (B, self.num_heads, N, N))
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, dropout_p=dropout_p, dropout_key=dropout_key,
            scale=self.scale, fused=False)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, self.dim_attn)
        x = self.proj(x)
        return self.proj_drop(x)


class Downsample2d(nnx.Module):
    """Pool (+ optional 1x1 expand) downsample (reference maxxvit.py:338-386)."""

    def __init__(self, dim: int, dim_out: int, pool_type: str = 'avg2', padding: str = '',
                 bias: bool = True, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert pool_type in ('max', 'max2', 'avg', 'avg2')
        if pool_type == 'max':
            self.pool = create_pool2d('max', kernel_size=3, stride=2, padding=padding or 1)
        elif pool_type == 'max2':
            self.pool = create_pool2d('max', 2, padding=padding or 0)
        elif pool_type == 'avg':
            self.pool = create_pool2d('avg', kernel_size=3, stride=2, padding=padding or 1)
        else:
            self.pool = create_pool2d('avg', 2, padding=padding or 0)
        if dim != dim_out:
            self.expand = nnx.Conv(
                dim, dim_out, kernel_size=(1, 1), use_bias=bias,
                kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.expand = None

    def __call__(self, x):
        x = self.pool(x)
        if self.expand is not None:
            x = self.expand(x)
        return x


class _NormDown(nnx.Module):
    """norm1 = Sequential(norm, down) container matching torch key layout."""

    def __init__(self, norm, down):
        self.norm = norm
        self.down = down

    def __call__(self, x):
        return self.down(self.norm(x))


class TransformerBlock2d(nnx.Module):
    """Full-grid transformer block for CoAtNet 'T' stages
    (reference maxxvit.py:413-492)."""

    def __init__(self, dim: int, dim_out: int, stride: int = 1,
                 rel_pos_cls: Optional[Callable] = None,
                 cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(), drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_layer = partial(get_norm_layer(cfg.norm_layer), eps=cfg.norm_eps)
        act_layer = cfg.act_layer

        if stride == 2:
            self.shortcut = Downsample2d(dim, dim_out, pool_type=cfg.pool_type, bias=cfg.shortcut_bias, **kw)
            self.norm1 = _NormDown(
                norm_layer(dim, rngs=rngs),
                Downsample2d(dim, dim, pool_type=cfg.pool_type, **kw))
        else:
            assert dim == dim_out
            self.shortcut = None
            self.norm1 = norm_layer(dim, rngs=rngs)

        self.attn = MaxxAttention(
            dim, dim_out, dim_head=cfg.dim_head, expand_first=cfg.expand_first,
            bias=cfg.attn_bias, head_first=cfg.head_first, rel_pos_cls=rel_pos_cls,
            attn_drop=cfg.attn_drop, proj_drop=cfg.proj_drop, **kw)
        self.ls1 = LayerScale2d(dim_out, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)

        self.norm2 = norm_layer(dim_out, rngs=rngs)
        self.mlp = ConvMlp(
            dim_out, hidden_features=int(dim_out * cfg.expand_ratio), act_layer=act_layer,
            drop=cfg.proj_drop, **kw)
        self.ls2 = LayerScale2d(dim_out, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def _attn(self, x):
        B, H, W, C = x.shape
        y = self.attn(x.reshape(B, H * W, C))
        return y.reshape(B, H, W, -1)

    def __call__(self, x, shared_rel_pos=None):
        shortcut = self.shortcut(x) if self.shortcut is not None else x
        y = self._attn(self.norm1(x))
        if self.ls1 is not None:
            y = self.ls1(y)
        x = shortcut + self.drop_path1(y)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + self.drop_path2(y)
        return x


def num_groups(group_size: Optional[int], channels: int) -> int:
    if not group_size:
        return 1
    assert channels % group_size == 0
    return channels // group_size


class MbConvBlock(nnx.Module):
    """Pre-norm inverted-bottleneck conv block (reference maxxvit.py:528-637)."""

    def __init__(self, in_chs: int, out_chs: int, stride: int = 1,
                 dilation: Tuple[int, int] = (1, 1),
                 cfg: MaxxVitConvCfg = MaxxVitConvCfg(), drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_act_layer = partial(get_norm_act_layer(cfg.norm_layer, cfg.act_layer), eps=cfg.norm_eps)
        mid_chs = make_divisible((out_chs if cfg.expand_output else in_chs) * cfg.expand_ratio)
        groups = num_groups(cfg.group_size, mid_chs)

        if stride == 2:
            self.shortcut = Downsample2d(
                in_chs, out_chs, pool_type=cfg.pool_type, bias=cfg.output_bias, padding=cfg.padding, **kw)
        else:
            self.shortcut = None

        assert cfg.stride_mode in ('pool', '1x1', 'dw')
        stride_pool, stride_1, stride_2 = 1, 1, 1
        dilation_2 = dilation[1]
        if cfg.stride_mode == 'pool':
            stride_pool = stride
        elif cfg.stride_mode == '1x1':
            stride_1 = stride
        else:
            stride_2, dilation_2 = stride, dilation[0]

        self.pre_norm = norm_act_layer(in_chs, apply_act=cfg.pre_norm_act, rngs=rngs)
        if stride_pool > 1:
            self.down = Downsample2d(in_chs, in_chs, pool_type=cfg.downsample_pool_type,
                                     padding=cfg.padding, **kw)
        else:
            self.down = None
        self.conv1_1x1 = create_conv2d(in_chs, mid_chs, 1, stride=stride_1, **kw)
        self.norm1 = norm_act_layer(mid_chs, rngs=rngs)
        self.conv2_kxk = create_conv2d(
            mid_chs, mid_chs, cfg.kernel_size, stride=stride_2, dilation=dilation_2,
            groups=groups, padding=cfg.padding, **kw)

        attn_kwargs = {}
        if cfg.attn_layer in ('se', 'eca'):
            attn_kwargs['act_layer'] = cfg.attn_act_layer
            attn_kwargs['rd_channels'] = int(cfg.attn_ratio * (out_chs if cfg.expand_output else mid_chs))
        if cfg.attn_early:
            self.se_early = create_attn(cfg.attn_layer, mid_chs, rngs=rngs, **attn_kwargs)
            self.norm2 = norm_act_layer(mid_chs, rngs=rngs)
            self.se = None
        else:
            self.se_early = None
            self.norm2 = norm_act_layer(mid_chs, rngs=rngs)
            self.se = create_attn(cfg.attn_layer, mid_chs, rngs=rngs, **attn_kwargs)

        self.conv3_1x1 = create_conv2d(mid_chs, out_chs, 1, bias=cfg.output_bias, **kw)
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        shortcut = self.shortcut(x) if self.shortcut is not None else x
        x = self.pre_norm(x)
        if self.down is not None:
            x = self.down(x)
        x = self.conv1_1x1(x)
        x = self.norm1(x)
        x = self.conv2_kxk(x)
        if self.se_early is not None:
            x = self.se_early(x)
        x = self.norm2(x)
        if self.se is not None:
            x = self.se(x)
        x = self.conv3_1x1(x)
        return self.drop_path(x) + shortcut


class ConvNeXtBlock(nnx.Module):
    """ConvNeXt block for 'maxxvit'/'coatnext' configs (reference
    maxxvit.py:639-739, conv_mlp path; NHWC makes conv_mlp/mlp identical)."""

    def __init__(self, in_chs: int, out_chs: Optional[int] = None, kernel_size: int = 7,
                 stride: int = 1, dilation: Tuple[int, int] = (1, 1),
                 cfg: MaxxVitConvCfg = MaxxVitConvCfg(), drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        out_chs = out_chs or in_chs
        norm_layer = partial(get_norm_layer(cfg.norm_layer), eps=cfg.norm_eps)

        if stride == 2:
            self.shortcut = Downsample2d(in_chs, out_chs, **kw)
        elif in_chs != out_chs:
            self.shortcut = nnx.Conv(
                in_chs, out_chs, kernel_size=(1, 1), use_bias=cfg.output_bias,
                kernel_init=trunc_normal_tf_(std=0.02), bias_init=zeros_,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.shortcut = None

        assert cfg.stride_mode in ('pool', 'dw')
        stride_pool, stride_dw = 1, 1
        if cfg.stride_mode == 'pool':
            stride_pool = stride
        else:
            stride_dw = stride
        if stride_pool == 2:
            self.down = Downsample2d(in_chs, in_chs, pool_type=cfg.downsample_pool_type, **kw)
        else:
            self.down = None

        self.conv_dw = create_conv2d(
            in_chs, out_chs, kernel_size=kernel_size, stride=stride_dw, dilation=dilation[1],
            depthwise=True, bias=cfg.output_bias, **kw)
        self.norm = norm_layer(out_chs, rngs=rngs)
        self.mlp = ConvMlp(
            out_chs, int(cfg.expand_ratio * out_chs), bias=cfg.output_bias,
            act_layer=cfg.act_layer, **kw)
        self.ls = LayerScale2d(out_chs, cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        shortcut = self.shortcut(x) if self.shortcut is not None else x
        if self.down is not None:
            x = self.down(x)
        x = self.conv_dw(x)
        x = self.norm(x)
        x = self.mlp(x)
        if self.ls is not None:
            x = self.ls(x)
        return self.drop_path(x) + shortcut


class PartitionAttention(nnx.Module):
    """Window or grid partition + attention + FFN (serves both
    PartitionAttentionCl and PartitionAttention2d — reference
    maxxvit.py:794-862, 992-1068)."""

    def __init__(self, dim: int, partition_type: str = 'block',
                 cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(), drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        norm_layer = partial(get_norm_layer(cfg.norm_layer_cl), eps=cfg.norm_eps)
        self.partition_block = partition_type == 'block'
        self.partition_size = to_2tuple(cfg.window_size if self.partition_block else cfg.grid_size)
        rel_pos_cls = get_rel_pos_cls(cfg, self.partition_size)

        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = MaxxAttention(
            dim, dim, dim_head=cfg.dim_head, bias=cfg.attn_bias, head_first=cfg.head_first,
            rel_pos_cls=rel_pos_cls, attn_drop=cfg.attn_drop, proj_drop=cfg.proj_drop, **kw)
        self.ls1 = LayerScale(dim, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)

        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * cfg.expand_ratio), act_layer=cfg.act_layer,
                       drop=cfg.proj_drop, **kw)
        self.ls2 = LayerScale(dim, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def _partition_attn(self, x):
        B, H, W, C = x.shape
        if self.partition_block:
            part = window_partition(x, self.partition_size)
            part = self.attn(part)
            return window_reverse(part, self.partition_size, H, W)
        part = grid_partition(x, self.partition_size)
        part = self.attn(part)
        return grid_reverse(part, self.partition_size, H, W)

    def __call__(self, x):
        y = self._partition_attn(self.norm1(x))
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + self.drop_path1(y)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + self.drop_path2(y)
        return x


class ParallelPartitionAttention(nnx.Module):
    """Parallel window+grid halves, one FFN (reference maxxvit.py:865-949)."""

    def __init__(self, dim: int, cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(),
                 drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        assert dim % 2 == 0
        norm_layer = partial(get_norm_layer(cfg.norm_layer_cl), eps=cfg.norm_eps)
        assert cfg.window_size == cfg.grid_size
        self.partition_size = to_2tuple(cfg.window_size)
        rel_pos_cls = get_rel_pos_cls(cfg, self.partition_size)

        self.norm1 = norm_layer(dim, rngs=rngs)
        attn_kw = dict(
            dim_head=cfg.dim_head, bias=cfg.attn_bias, head_first=cfg.head_first,
            rel_pos_cls=rel_pos_cls, attn_drop=cfg.attn_drop, proj_drop=cfg.proj_drop, **kw)
        self.attn_block = MaxxAttention(dim, dim // 2, **attn_kw)
        self.attn_grid = MaxxAttention(dim, dim // 2, **attn_kw)
        self.ls1 = LayerScale(dim, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)

        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * cfg.expand_ratio), out_features=dim,
                       act_layer=cfg.act_layer, drop=cfg.proj_drop, **kw)
        self.ls2 = LayerScale(dim, init_values=cfg.init_values, param_dtype=param_dtype, rngs=rngs) \
            if cfg.init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def _partition_attn(self, x):
        B, H, W, C = x.shape
        pb = window_partition(x, self.partition_size)
        pb = self.attn_block(pb)
        xw = window_reverse(pb, self.partition_size, H, W)
        pg = grid_partition(x, self.partition_size)
        pg = self.attn_grid(pg)
        xg = grid_reverse(pg, self.partition_size, H, W)
        return jnp.concatenate([xw, xg], axis=-1)

    def __call__(self, x):
        y = self._partition_attn(self.norm1(x))
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + self.drop_path1(y)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + self.drop_path2(y)
        return x


class MaxxVitBlock(nnx.Module):
    """MBConv (or ConvNeXt) + window attn + grid attn (reference
    maxxvit.py:1070-1124)."""

    def __init__(self, dim: int, dim_out: int, stride: int = 1,
                 conv_cfg: MaxxVitConvCfg = MaxxVitConvCfg(),
                 transformer_cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(),
                 drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        conv_cls = ConvNeXtBlock if conv_cfg.block_type == 'convnext' else MbConvBlock
        self.conv = conv_cls(dim, dim_out, stride=stride, cfg=conv_cfg, drop_path=drop_path, **kw)
        attn_kw = dict(dim=dim_out, cfg=transformer_cfg, drop_path=drop_path, **kw)
        self.attn_block = None if transformer_cfg.no_block_attn else PartitionAttention(**attn_kw)
        self.attn_grid = PartitionAttention(partition_type='grid', **attn_kw)

    def __call__(self, x):
        x = self.conv(x)
        if self.attn_block is not None:
            x = self.attn_block(x)
        return self.attn_grid(x)


class ParallelMaxxVitBlock(nnx.Module):
    """Convs + parallel window/grid attention (reference maxxvit.py:1126-1176)."""

    def __init__(self, dim, dim_out, stride=1, num_conv=2,
                 conv_cfg: MaxxVitConvCfg = MaxxVitConvCfg(),
                 transformer_cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(),
                 drop_path: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        conv_cls = ConvNeXtBlock if conv_cfg.block_type == 'convnext' else MbConvBlock
        if num_conv > 1:
            convs = [conv_cls(dim, dim_out, stride=stride, cfg=conv_cfg, drop_path=drop_path, **kw)]
            convs += [conv_cls(dim_out, dim_out, cfg=conv_cfg, drop_path=drop_path, **kw)
                      for _ in range(num_conv - 1)]
            self.conv = nnx.List(convs)
        else:
            self.conv = conv_cls(dim, dim_out, stride=stride, cfg=conv_cfg, drop_path=drop_path, **kw)
        self.attn = ParallelPartitionAttention(dim=dim_out, cfg=transformer_cfg, drop_path=drop_path, **kw)

    def __call__(self, x):
        if isinstance(self.conv, nnx.List):
            for c in self.conv:
                x = c(x)
        else:
            x = self.conv(x)
        return self.attn(x)


class MaxxVitStage(nnx.Module):
    """Mixed conv/transformer stage (reference maxxvit.py:1178-1266)."""

    def __init__(
            self, in_chs: int, out_chs: int, stride: int = 2, depth: int = 4,
            feat_size: Tuple[int, int] = (14, 14), block_types: Union[str, Tuple[str, ...]] = 'C',
            transformer_cfg: MaxxVitTransformerCfg = MaxxVitTransformerCfg(),
            conv_cfg: MaxxVitConvCfg = MaxxVitConvCfg(),
            drop_path: Union[float, List[float]] = 0.0,
            *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.grad_checkpointing = False
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        block_types = extend_tuple(block_types, depth)
        blocks = []
        for i, t in enumerate(block_types):
            block_stride = stride if i == 0 else 1
            assert t in ('C', 'T', 'M', 'PM')
            dp = drop_path[i] if isinstance(drop_path, (list, tuple)) else drop_path
            if t == 'C':
                conv_cls = ConvNeXtBlock if conv_cfg.block_type == 'convnext' else MbConvBlock
                blocks.append(conv_cls(in_chs, out_chs, stride=block_stride, cfg=conv_cfg, drop_path=dp, **kw))
            elif t == 'T':
                rel_pos_cls = get_rel_pos_cls(transformer_cfg, feat_size)
                blocks.append(TransformerBlock2d(
                    in_chs, out_chs, stride=block_stride, rel_pos_cls=rel_pos_cls,
                    cfg=transformer_cfg, drop_path=dp, **kw))
            elif t == 'M':
                blocks.append(MaxxVitBlock(
                    in_chs, out_chs, stride=block_stride, conv_cfg=conv_cfg,
                    transformer_cfg=transformer_cfg, drop_path=dp, **kw))
            else:  # 'PM'
                blocks.append(ParallelMaxxVitBlock(
                    in_chs, out_chs, stride=block_stride, conv_cfg=conv_cfg,
                    transformer_cfg=transformer_cfg, drop_path=dp, **kw))
            in_chs = out_chs
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class Stem(nnx.Module):
    """Two-conv stride-2 stem (reference maxxvit.py:1268-1316)."""

    def __init__(self, in_chs: int, out_chs, kernel_size: int = 3, padding: str = '',
                 bias: bool = False, act_layer: str = 'gelu', norm_layer: str = 'batchnorm2d',
                 norm_eps: float = 1e-5,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        out_chs = to_2tuple(out_chs)
        norm_act_layer = partial(get_norm_act_layer(norm_layer, act_layer), eps=norm_eps)
        self.out_chs = out_chs[-1]
        self.stride = 2
        self.conv1 = create_conv2d(in_chs, out_chs[0], kernel_size, stride=2, padding=padding, bias=bias, **kw)
        self.norm1 = norm_act_layer(out_chs[0], rngs=rngs)
        self.conv2 = create_conv2d(out_chs[0], out_chs[1], kernel_size, stride=1, padding=padding, bias=bias, **kw)

    def __call__(self, x):
        return self.conv2(self.norm1(self.conv1(x)))


def cfg_window_size(cfg: MaxxVitTransformerCfg, img_size: Tuple[int, int]) -> MaxxVitTransformerCfg:
    if cfg.window_size is not None:
        assert cfg.grid_size
        return cfg
    partition_size = img_size[0] // cfg.partition_ratio, img_size[1] // cfg.partition_ratio
    return replace(cfg, window_size=partition_size, grid_size=partition_size)


def _overlay_kwargs(cfg: MaxxVitCfg, **kwargs):
    transformer_kwargs, conv_kwargs, base_kwargs = {}, {}, {}
    for k, v in kwargs.items():
        if k.startswith('transformer_'):
            transformer_kwargs[k.replace('transformer_', '')] = v
        elif k.startswith('conv_'):
            conv_kwargs[k.replace('conv_', '')] = v
        else:
            base_kwargs[k] = v
    return replace(
        cfg,
        transformer_cfg=replace(cfg.transformer_cfg, **transformer_kwargs),
        conv_cfg=replace(cfg.conv_cfg, **conv_kwargs),
        **base_kwargs,
    )


class MaxxVit(nnx.Module):
    """CoAtNet + MaxViT trunk with the reference's model contract
    (reference maxxvit.py:1349-1577)."""

    def __init__(
            self,
            cfg: MaxxVitCfg,
            img_size: Union[int, Tuple[int, int]] = 224,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
            **kwargs,
    ):
        img_size = to_2tuple(img_size)
        if kwargs:
            cfg = _overlay_kwargs(cfg, **kwargs)
        transformer_cfg = cfg_window_size(cfg.transformer_cfg, img_size)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.embed_dim = cfg.embed_dim[-1]
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        self.feature_info = []
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.stem = Stem(
            in_chs=in_chans, out_chs=cfg.stem_width, padding=cfg.conv_cfg.padding,
            bias=cfg.stem_bias, act_layer=cfg.conv_cfg.act_layer,
            norm_layer=cfg.conv_cfg.norm_layer, norm_eps=cfg.conv_cfg.norm_eps, **kw)
        stride = self.stem.stride
        self.feature_info += [dict(num_chs=self.stem.out_chs, reduction=2, module='stem')]
        feat_size = tuple(i // s for i, s in zip(img_size, to_2tuple(stride)))

        num_stages = len(cfg.embed_dim)
        assert len(cfg.depths) == num_stages
        dpr = calculate_drop_path_rates(drop_path_rate, list(cfg.depths), stagewise=True)
        in_chs = self.stem.out_chs
        stages = []
        for i in range(num_stages):
            stage_stride = 2
            out_chs = cfg.embed_dim[i]
            feat_size = tuple((r - 1) // stage_stride + 1 for r in feat_size)
            stages.append(MaxxVitStage(
                in_chs, out_chs, depth=cfg.depths[i], block_types=cfg.block_type[i],
                conv_cfg=cfg.conv_cfg, transformer_cfg=transformer_cfg,
                feat_size=feat_size, drop_path=dpr[i], **kw))
            stride *= stage_stride
            in_chs = out_chs
            self.feature_info += [dict(num_chs=out_chs, reduction=stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)

        final_norm_layer = partial(get_norm_layer(cfg.transformer_cfg.norm_layer),
                                   eps=cfg.transformer_cfg.norm_eps)
        if cfg.head_hidden_size:
            self.norm = None
            self.head_hidden_size = cfg.head_hidden_size
            self.head = NormMlpClassifierHead(
                self.num_features, num_classes, hidden_size=self.head_hidden_size,
                pool_type=global_pool, drop_rate=drop_rate, norm_layer=final_norm_layer, **kw)
        else:
            self.head_hidden_size = self.num_features
            self.norm = final_norm_layer(self.num_features, rngs=rngs)
            self.head = ClassifierHead(
                self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate, **kw)

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self):
        return {'relative_position_bias_table', 'rel_pos.mlp'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=[(r'^stages\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        intermediates = []
        feat_idx = 0
        x = self.stem(x)
        if feat_idx in take_indices:
            intermediates.append(x)
        last_idx = len(self.stages)
        stages = self.stages if not stop_early else list(self.stages)[:max_index]
        for stage in stages:
            feat_idx += 1
            x = stage(x)
            if feat_idx in take_indices:
                x_inter = self.norm(x) if (norm and self.norm is not None and feat_idx == last_idx) else x
                intermediates.append(x_inter)
        if intermediates_only:
            return intermediates
        if feat_idx == last_idx and self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        self.stages = nnx.List(list(self.stages)[:max_index])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        if k.endswith(('relative_position_index', 'height_lookup', 'width_lookup')):
            continue
        # torch NormMlpClassifierHead nests pre_logits as Sequential('fc','act')
        k = k.replace('head.pre_logits.fc.', 'head.pre_logits_fc.')
        out[k] = v
    return convert_torch_state_dict(out, model)


# ---------------------------------------------------------------------------
# config constructors — values mirror reference maxxvit.py:1580-1747 exactly
# (recipe data, kept verbatim so released checkpoints/configs transfer)
# ---------------------------------------------------------------------------

def _rw_coat_cfg(
        stride_mode='pool', pool_type='avg2', conv_output_bias=False, conv_attn_early=False,
        conv_attn_act_layer='relu', conv_norm_layer='', transformer_shortcut_bias=True,
        transformer_norm_layer='layernorm2d', transformer_norm_layer_cl='layernorm',
        init_values=None, rel_pos_type='bias', rel_pos_dim=512):
    return dict(
        conv_cfg=MaxxVitConvCfg(
            stride_mode=stride_mode, pool_type=pool_type, pre_norm_act=True,
            expand_output=False, output_bias=conv_output_bias, attn_early=conv_attn_early,
            attn_act_layer=conv_attn_act_layer, act_layer='silu', norm_layer=conv_norm_layer),
        transformer_cfg=MaxxVitTransformerCfg(
            expand_first=False, shortcut_bias=transformer_shortcut_bias, pool_type=pool_type,
            init_values=init_values, norm_layer=transformer_norm_layer,
            norm_layer_cl=transformer_norm_layer_cl, rel_pos_type=rel_pos_type,
            rel_pos_dim=rel_pos_dim),
    )


def _rw_max_cfg(
        stride_mode='dw', pool_type='avg2', conv_output_bias=False, conv_attn_ratio=1 / 16,
        conv_norm_layer='', transformer_norm_layer='layernorm2d',
        transformer_norm_layer_cl='layernorm', window_size=None, dim_head=32,
        init_values=None, rel_pos_type='bias', rel_pos_dim=512):
    return dict(
        conv_cfg=MaxxVitConvCfg(
            stride_mode=stride_mode, pool_type=pool_type, expand_output=False,
            output_bias=conv_output_bias, attn_ratio=conv_attn_ratio, act_layer='silu',
            norm_layer=conv_norm_layer),
        transformer_cfg=MaxxVitTransformerCfg(
            expand_first=False, pool_type=pool_type, dim_head=dim_head, window_size=window_size,
            init_values=init_values, norm_layer=transformer_norm_layer,
            norm_layer_cl=transformer_norm_layer_cl, rel_pos_type=rel_pos_type,
            rel_pos_dim=rel_pos_dim),
    )


def _next_cfg(
        stride_mode='dw', pool_type='avg2', conv_norm_layer='layernorm2d',
        conv_norm_layer_cl='layernorm', transformer_norm_layer='layernorm2d',
        transformer_norm_layer_cl='layernorm', window_size=None, no_block_attn=False,
        init_values=1e-6, rel_pos_type='mlp', rel_pos_dim=512):
    init_values = to_2tuple(init_values)
    return dict(
        conv_cfg=MaxxVitConvCfg(
            block_type='convnext', stride_mode=stride_mode, pool_type=pool_type,
            expand_output=False, init_values=init_values[0], norm_layer=conv_norm_layer,
            norm_layer_cl=conv_norm_layer_cl),
        transformer_cfg=MaxxVitTransformerCfg(
            expand_first=False, pool_type=pool_type, window_size=window_size,
            no_block_attn=no_block_attn, init_values=init_values[1],
            norm_layer=transformer_norm_layer, norm_layer_cl=transformer_norm_layer_cl,
            rel_pos_type=rel_pos_type, rel_pos_dim=rel_pos_dim),
    )


def _tf_cfg():
    return dict(
        conv_cfg=MaxxVitConvCfg(norm_eps=1e-3, act_layer='gelu_tanh', padding='same'),
        transformer_cfg=MaxxVitTransformerCfg(
            norm_eps=1e-5, act_layer='gelu_tanh', head_first=False, rel_pos_type='bias_tf'),
    )


model_cfgs = dict(
    # timm-specific CoAtNet configs
    coatnet_pico_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 3, 5, 2), stem_width=(32, 64),
        **_rw_max_cfg(conv_output_bias=True, conv_attn_ratio=0.25)),
    coatnet_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(3, 4, 6, 3), stem_width=(32, 64),
        **_rw_max_cfg(stride_mode='pool', conv_output_bias=True, conv_attn_ratio=0.25)),
    coatnet_0_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 3, 7, 2), stem_width=(32, 64),
        **_rw_coat_cfg(conv_attn_early=True, transformer_shortcut_bias=False)),
    coatnet_1_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2), stem_width=(32, 64),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_early=True, transformer_shortcut_bias=False)),
    coatnet_2_rw=MaxxVitCfg(
        embed_dim=(128, 256, 512, 1024), depths=(2, 6, 14, 2), stem_width=(64, 128),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_act_layer='silu')),
    coatnet_3_rw=MaxxVitCfg(
        embed_dim=(192, 384, 768, 1536), depths=(2, 6, 14, 2), stem_width=(96, 192),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_act_layer='silu', init_values=1e-6)),
    coatnet_bn_0_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 3, 7, 2), stem_width=(32, 64),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_early=True, transformer_shortcut_bias=False,
                       transformer_norm_layer='batchnorm2d')),
    coatnet_rmlp_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(3, 4, 6, 3), stem_width=(32, 64),
        **_rw_max_cfg(conv_output_bias=True, conv_attn_ratio=0.25, rel_pos_type='mlp',
                      rel_pos_dim=384)),
    coatnet_rmlp_0_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 3, 7, 2), stem_width=(32, 64),
        **_rw_coat_cfg(stride_mode='dw', rel_pos_type='mlp')),
    coatnet_rmlp_1_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2), stem_width=(32, 64),
        **_rw_coat_cfg(pool_type='max', conv_attn_early=True, transformer_shortcut_bias=False,
                       rel_pos_type='mlp', rel_pos_dim=384)),
    coatnet_rmlp_1_rw2=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2), stem_width=(32, 64),
        **_rw_coat_cfg(stride_mode='dw', rel_pos_type='mlp', rel_pos_dim=512)),
    coatnet_rmlp_2_rw=MaxxVitCfg(
        embed_dim=(128, 256, 512, 1024), depths=(2, 6, 14, 2), stem_width=(64, 128),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_act_layer='silu', init_values=1e-6,
                       rel_pos_type='mlp')),
    coatnet_rmlp_3_rw=MaxxVitCfg(
        embed_dim=(192, 384, 768, 1536), depths=(2, 6, 14, 2), stem_width=(96, 192),
        **_rw_coat_cfg(stride_mode='dw', conv_attn_act_layer='silu', init_values=1e-6,
                       rel_pos_type='mlp')),
    coatnet_nano_cc=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(3, 4, 6, 3), stem_width=(32, 64),
        block_type=('C', 'C', ('C', 'T'), ('C', 'T')), **_rw_coat_cfg()),
    coatnext_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(3, 4, 6, 3), stem_width=(32, 64),
        weight_init='normal', **_next_cfg(rel_pos_type='bias', init_values=(1e-5, None))),

    # CoAtNet paper-like configs
    coatnet_0=MaxxVitCfg(embed_dim=(96, 192, 384, 768), depths=(2, 3, 5, 2),
                         stem_width=64, head_hidden_size=768),
    coatnet_1=MaxxVitCfg(embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2),
                         stem_width=64, head_hidden_size=768),
    coatnet_2=MaxxVitCfg(embed_dim=(128, 256, 512, 1024), depths=(2, 6, 14, 2),
                         stem_width=128, head_hidden_size=1024),
    coatnet_3=MaxxVitCfg(embed_dim=(192, 384, 768, 1536), depths=(2, 6, 14, 2),
                         stem_width=192, head_hidden_size=1536),
    coatnet_4=MaxxVitCfg(embed_dim=(192, 384, 768, 1536), depths=(2, 12, 28, 2),
                         stem_width=192, head_hidden_size=1536),
    coatnet_5=MaxxVitCfg(embed_dim=(256, 512, 1280, 2048), depths=(2, 12, 28, 2),
                         stem_width=192, head_hidden_size=2048),

    # Experimental MaxVit configs
    maxvit_pico_rw=MaxxVitCfg(
        embed_dim=(32, 64, 128, 256), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(24, 32), **_rw_max_cfg()),
    maxvit_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(1, 2, 3, 1), block_type=('M',) * 4,
        stem_width=(32, 64), **_rw_max_cfg()),
    maxvit_tiny_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(32, 64), **_rw_max_cfg()),
    maxvit_tiny_pm=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 2, 5, 2), block_type=('PM',) * 4,
        stem_width=(32, 64), **_rw_max_cfg()),
    maxvit_rmlp_pico_rw=MaxxVitCfg(
        embed_dim=(32, 64, 128, 256), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(24, 32), **_rw_max_cfg(rel_pos_type='mlp')),
    maxvit_rmlp_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(1, 2, 3, 1), block_type=('M',) * 4,
        stem_width=(32, 64), **_rw_max_cfg(rel_pos_type='mlp')),
    maxvit_rmlp_tiny_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(32, 64), **_rw_max_cfg(rel_pos_type='mlp')),
    maxvit_rmlp_small_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(32, 64), **_rw_max_cfg(rel_pos_type='mlp', init_values=1e-6)),
    maxvit_rmlp_base_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2), block_type=('M',) * 4,
        stem_width=(32, 64), head_hidden_size=768, **_rw_max_cfg(rel_pos_type='mlp')),

    maxxvit_rmlp_nano_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(1, 2, 3, 1), block_type=('M',) * 4,
        stem_width=(32, 64), weight_init='normal', **_next_cfg()),
    maxxvit_rmlp_tiny_rw=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(32, 64), **_next_cfg()),
    maxxvit_rmlp_small_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=(48, 96), **_next_cfg()),
    maxxvitv2_nano_rw=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(1, 2, 3, 1), block_type=('M',) * 4,
        stem_width=(48, 96), weight_init='normal',
        **_next_cfg(no_block_attn=True, rel_pos_type='bias')),
    maxxvitv2_rmlp_base_rw=MaxxVitCfg(
        embed_dim=(128, 256, 512, 1024), depths=(2, 6, 12, 2), block_type=('M',) * 4,
        stem_width=(64, 128), **_next_cfg(no_block_attn=True)),
    maxxvitv2_rmlp_large_rw=MaxxVitCfg(
        embed_dim=(160, 320, 640, 1280), depths=(2, 6, 16, 2), block_type=('M',) * 4,
        stem_width=(80, 160), head_hidden_size=1280, **_next_cfg(no_block_attn=True)),

    # MaxViT paper (TF port) configs
    maxvit_tiny_tf=MaxxVitCfg(
        embed_dim=(64, 128, 256, 512), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=64, stem_bias=True, head_hidden_size=512, **_tf_cfg()),
    maxvit_small_tf=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 2, 5, 2), block_type=('M',) * 4,
        stem_width=64, stem_bias=True, head_hidden_size=768, **_tf_cfg()),
    maxvit_base_tf=MaxxVitCfg(
        embed_dim=(96, 192, 384, 768), depths=(2, 6, 14, 2), block_type=('M',) * 4,
        stem_width=64, stem_bias=True, head_hidden_size=768, **_tf_cfg()),
    maxvit_large_tf=MaxxVitCfg(
        embed_dim=(128, 256, 512, 1024), depths=(2, 6, 14, 2), block_type=('M',) * 4,
        stem_width=128, stem_bias=True, head_hidden_size=1024, **_tf_cfg()),
    maxvit_xlarge_tf=MaxxVitCfg(
        embed_dim=(192, 384, 768, 1536), depths=(2, 6, 14, 2), block_type=('M',) * 4,
        stem_width=192, stem_bias=True, head_hidden_size=1536, **_tf_cfg()),

    test_maxxvit=MaxxVitCfg(
        embed_dim=(16, 32, 48), depths=(1, 1, 1), block_type=('C', 'M', 'T'),
        stem_width=(8, 16), **_rw_max_cfg()),
)


def _create_maxxvit(variant, cfg_variant=None, pretrained=False, **kwargs):
    if cfg_variant is None:
        if variant in model_cfgs:
            cfg_variant = variant
        else:
            cfg_variant = '_'.join(variant.split('_')[:-1])
    return build_model_with_cfg(
        MaxxVit, variant, pretrained,
        model_cfg=model_cfgs[cfg_variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.95,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'stem.conv1',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'coatnet_pico_rw_224.untrained': _cfg(),
    'coatnet_nano_rw_224.sw_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'coatnet_0_rw_224.sw_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_1_rw_224.sw_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_2_rw_224.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_3_rw_224.untrained': _cfg(),
    'coatnet_bn_0_rw_224.sw_in1k': _cfg(
        hf_hub_id='timm/', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'coatnet_rmlp_nano_rw_224.sw_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'coatnet_rmlp_0_rw_224.untrained': _cfg(),
    'coatnet_rmlp_1_rw_224.sw_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_rmlp_1_rw2_224.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_rmlp_2_rw_224.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'coatnet_rmlp_2_rw_384.sw_in12k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'coatnet_rmlp_3_rw_224.untrained': _cfg(),
    'coatnet_nano_cc_224.untrained': _cfg(),
    'coatnext_nano_rw_224.sw_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'coatnet_0_224.untrained': _cfg(),
    'coatnet_1_224.untrained': _cfg(),
    'coatnet_2_224.untrained': _cfg(),
    'coatnet_3_224.untrained': _cfg(),
    'coatnet_4_224.untrained': _cfg(),
    'coatnet_5_224.untrained': _cfg(),

    'maxvit_pico_rw_256.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_nano_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_tiny_rw_224.sw_in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_tiny_rw_256.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_tiny_pm_256.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_rmlp_pico_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_rmlp_nano_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_rmlp_tiny_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_rmlp_small_rw_224.sw_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'maxvit_rmlp_small_rw_256.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxvit_rmlp_base_rw_224.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_rmlp_base_rw_384.sw_in12k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),

    'maxxvit_rmlp_nano_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxxvit_rmlp_tiny_rw_256.untrained': _cfg(input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxxvit_rmlp_small_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxxvitv2_nano_rw_256.sw_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'maxxvitv2_rmlp_base_rw_224.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'maxxvitv2_rmlp_base_rw_384.sw_in12k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxxvitv2_rmlp_large_rw_224.untrained': _cfg(),

    'maxvit_tiny_tf_224.in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_tiny_tf_384.in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxvit_tiny_tf_512.in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0),
    'maxvit_small_tf_224.in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_small_tf_384.in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxvit_small_tf_512.in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0),
    'maxvit_base_tf_224.in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_base_tf_384.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxvit_base_tf_512.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0),
    'maxvit_large_tf_224.in1k': _cfg(hf_hub_id='timm/'),
    'maxvit_large_tf_384.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxvit_large_tf_512.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0),
    'maxvit_xlarge_tf_224.in21k': _cfg(hf_hub_id='timm/', num_classes=21843),
    'maxvit_xlarge_tf_384.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'maxvit_xlarge_tf_512.in21k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0),

    'test_maxxvit.untrained': _cfg(input_size=(3, 96, 96), pool_size=(3, 3)),
})


def _make_entry(name: str, cfg_variant: str, img_size: Optional[int] = None):
    def entrypoint(pretrained=False, **kwargs):
        if img_size is not None and img_size != 224:
            kwargs.setdefault('img_size', img_size)
        return _create_maxxvit(name, cfg_variant=cfg_variant, pretrained=pretrained, **kwargs)
    entrypoint.__name__ = name
    entrypoint.__doc__ = f'MaxxVit family model {name} (reference maxxvit.py entrypoints)'
    return register_model(entrypoint)


_entrypoints = [
    # (variant name, cfg key)
    ('coatnet_pico_rw_224', 'coatnet_pico_rw'),
    ('coatnet_nano_rw_224', 'coatnet_nano_rw'),
    ('coatnet_0_rw_224', 'coatnet_0_rw'),
    ('coatnet_1_rw_224', 'coatnet_1_rw'),
    ('coatnet_2_rw_224', 'coatnet_2_rw'),
    ('coatnet_3_rw_224', 'coatnet_3_rw'),
    ('coatnet_bn_0_rw_224', 'coatnet_bn_0_rw'),
    ('coatnet_rmlp_nano_rw_224', 'coatnet_rmlp_nano_rw'),
    ('coatnet_rmlp_0_rw_224', 'coatnet_rmlp_0_rw'),
    ('coatnet_rmlp_1_rw_224', 'coatnet_rmlp_1_rw'),
    ('coatnet_rmlp_1_rw2_224', 'coatnet_rmlp_1_rw2'),
    ('coatnet_rmlp_2_rw_224', 'coatnet_rmlp_2_rw'),
    ('coatnet_rmlp_2_rw_384', 'coatnet_rmlp_2_rw'),
    ('coatnet_rmlp_3_rw_224', 'coatnet_rmlp_3_rw'),
    ('coatnet_nano_cc_224', 'coatnet_nano_cc'),
    ('coatnext_nano_rw_224', 'coatnext_nano_rw'),
    ('coatnet_0_224', 'coatnet_0'),
    ('coatnet_1_224', 'coatnet_1'),
    ('coatnet_2_224', 'coatnet_2'),
    ('coatnet_3_224', 'coatnet_3'),
    ('coatnet_4_224', 'coatnet_4'),
    ('coatnet_5_224', 'coatnet_5'),
    ('maxvit_pico_rw_256', 'maxvit_pico_rw'),
    ('maxvit_nano_rw_256', 'maxvit_nano_rw'),
    ('maxvit_tiny_rw_224', 'maxvit_tiny_rw'),
    ('maxvit_tiny_rw_256', 'maxvit_tiny_rw'),
    ('maxvit_rmlp_pico_rw_256', 'maxvit_rmlp_pico_rw'),
    ('maxvit_rmlp_nano_rw_256', 'maxvit_rmlp_nano_rw'),
    ('maxvit_rmlp_tiny_rw_256', 'maxvit_rmlp_tiny_rw'),
    ('maxvit_rmlp_small_rw_224', 'maxvit_rmlp_small_rw'),
    ('maxvit_rmlp_small_rw_256', 'maxvit_rmlp_small_rw'),
    ('maxvit_rmlp_base_rw_224', 'maxvit_rmlp_base_rw'),
    ('maxvit_rmlp_base_rw_384', 'maxvit_rmlp_base_rw'),
    ('maxvit_tiny_pm_256', 'maxvit_tiny_pm'),
    ('maxxvit_rmlp_nano_rw_256', 'maxxvit_rmlp_nano_rw'),
    ('maxxvit_rmlp_tiny_rw_256', 'maxxvit_rmlp_tiny_rw'),
    ('maxxvit_rmlp_small_rw_256', 'maxxvit_rmlp_small_rw'),
    ('maxxvitv2_nano_rw_256', 'maxxvitv2_nano_rw'),
    ('maxxvitv2_rmlp_base_rw_224', 'maxxvitv2_rmlp_base_rw'),
    ('maxxvitv2_rmlp_base_rw_384', 'maxxvitv2_rmlp_base_rw'),
    ('maxxvitv2_rmlp_large_rw_224', 'maxxvitv2_rmlp_large_rw'),
    ('maxvit_tiny_tf_224', 'maxvit_tiny_tf'),
    ('maxvit_tiny_tf_384', 'maxvit_tiny_tf'),
    ('maxvit_tiny_tf_512', 'maxvit_tiny_tf'),
    ('maxvit_small_tf_224', 'maxvit_small_tf'),
    ('maxvit_small_tf_384', 'maxvit_small_tf'),
    ('maxvit_small_tf_512', 'maxvit_small_tf'),
    ('maxvit_base_tf_224', 'maxvit_base_tf'),
    ('maxvit_base_tf_384', 'maxvit_base_tf'),
    ('maxvit_base_tf_512', 'maxvit_base_tf'),
    ('maxvit_large_tf_224', 'maxvit_large_tf'),
    ('maxvit_large_tf_384', 'maxvit_large_tf'),
    ('maxvit_large_tf_512', 'maxvit_large_tf'),
    ('maxvit_xlarge_tf_224', 'maxvit_xlarge_tf'),
    ('maxvit_xlarge_tf_384', 'maxvit_xlarge_tf'),
    ('maxvit_xlarge_tf_512', 'maxvit_xlarge_tf'),
]

for _name, _cfg_key in _entrypoints:
    _size = int(_name.rsplit('_', 1)[-1])
    _make_entry(_name, _cfg_key, img_size=_size)


@register_model
def test_maxxvit(pretrained=False, **kwargs) -> MaxxVit:
    return _create_maxxvit('test_maxxvit', pretrained=pretrained, **kwargs)
