"""Pre-activation (v2) ResNets, incl. Big Transfer (BiT) variants
(reference: timm/models/resnetv2.py:1-1192; He et al. 2016 identity mappings,
Kolesnikov et al. 2019 BiT).

TPU-first notes: NHWC throughout; the BiT trunk (StdConv + GroupNorm) has no
batch statistics, so the whole network is a pure function — no train/eval BN
divergence and no cross-replica stat sync under pjit. The 'fixed' stem pool
reproduces BiT's zero-pad + VALID max-pool exactly (not -inf padding), which
matters for sign-indefinite pre-activation features.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNormAct2d, ClassifierHead, DropPath, EvoNorm2dS0, FilterResponseNormTlu2d,
    GroupNormAct, StdConv2d, calculate_drop_path_rates, create_conv2d, get_act_fn,
    get_norm_act_layer, make_divisible,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model
from .resnet import avg_pool2d, max_pool2d

__all__ = ['ResNetV2']


class PreActBasic(nnx.Module):
    """Pre-activation basic block (reference resnetv2.py:50-140)."""

    def __init__(self, in_chs, out_chs=None, bottle_ratio=1.0, stride=1, dilation=1,
                 first_dilation=None, groups=1, act_layer=None, conv_layer=None,
                 norm_layer=None, proj_layer=None, drop_path_rate=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        first_dilation = first_dilation or dilation
        conv_layer = conv_layer or StdConv2d
        norm_layer = norm_layer or partial(GroupNormAct, num_groups=32)
        out_chs = out_chs or in_chs
        mid_chs = make_divisible(out_chs * bottle_ratio)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if proj_layer is not None and (stride != 1 or first_dilation != dilation or in_chs != out_chs):
            self.downsample = proj_layer(
                in_chs, out_chs, stride=stride, dilation=dilation,
                first_dilation=first_dilation, preact=True,
                conv_layer=conv_layer, norm_layer=norm_layer, **dd)
        else:
            self.downsample = None

        self.norm1 = norm_layer(in_chs, **dd)
        self.conv1 = conv_layer(in_chs, mid_chs, 3, stride=stride, dilation=first_dilation, groups=groups, **dd)
        self.norm2 = norm_layer(mid_chs, **dd)
        self.conv2 = conv_layer(mid_chs, out_chs, 3, dilation=dilation, groups=groups, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def zero_init_last(self):
        self.conv2.kernel[...] = jnp.zeros_like(self.conv2.kernel[...])

    def __call__(self, x):
        x_preact = self.norm1(x)
        shortcut = x
        if self.downsample is not None:
            shortcut = self.downsample(x_preact)
        x = self.conv1(x_preact)
        x = self.conv2(self.norm2(x))
        x = self.drop_path(x)
        return x + shortcut


class PreActBottleneck(nnx.Module):
    """Pre-activation bottleneck block (reference resnetv2.py:142-241)."""

    def __init__(self, in_chs, out_chs=None, bottle_ratio=0.25, stride=1, dilation=1,
                 first_dilation=None, groups=1, act_layer=None, conv_layer=None,
                 norm_layer=None, proj_layer=None, drop_path_rate=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        first_dilation = first_dilation or dilation
        conv_layer = conv_layer or StdConv2d
        norm_layer = norm_layer or partial(GroupNormAct, num_groups=32)
        out_chs = out_chs or in_chs
        mid_chs = make_divisible(out_chs * bottle_ratio)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if proj_layer is not None:
            self.downsample = proj_layer(
                in_chs, out_chs, stride=stride, dilation=dilation,
                first_dilation=first_dilation, preact=True,
                conv_layer=conv_layer, norm_layer=norm_layer, **dd)
        else:
            self.downsample = None

        self.norm1 = norm_layer(in_chs, **dd)
        self.conv1 = conv_layer(in_chs, mid_chs, 1, **dd)
        self.norm2 = norm_layer(mid_chs, **dd)
        self.conv2 = conv_layer(mid_chs, mid_chs, 3, stride=stride, dilation=first_dilation, groups=groups, **dd)
        self.norm3 = norm_layer(mid_chs, **dd)
        self.conv3 = conv_layer(mid_chs, out_chs, 1, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def zero_init_last(self):
        self.conv3.kernel[...] = jnp.zeros_like(self.conv3.kernel[...])

    def __call__(self, x):
        x_preact = self.norm1(x)
        shortcut = x
        if self.downsample is not None:
            shortcut = self.downsample(x_preact)
        x = self.conv1(x_preact)
        x = self.conv2(self.norm2(x))
        x = self.conv3(self.norm3(x))
        x = self.drop_path(x)
        return x + shortcut


class Bottleneck(nnx.Module):
    """Post-activation bottleneck, v1.5-style (reference resnetv2.py:243-324)."""

    def __init__(self, in_chs, out_chs=None, bottle_ratio=0.25, stride=1, dilation=1,
                 first_dilation=None, groups=1, act_layer=None, conv_layer=None,
                 norm_layer=None, proj_layer=None, drop_path_rate=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        first_dilation = first_dilation or dilation
        act_layer = act_layer or 'relu'
        conv_layer = conv_layer or StdConv2d
        norm_layer = norm_layer or partial(GroupNormAct, num_groups=32)
        out_chs = out_chs or in_chs
        mid_chs = make_divisible(out_chs * bottle_ratio)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if proj_layer is not None:
            self.downsample = proj_layer(
                in_chs, out_chs, stride=stride, dilation=dilation, preact=False,
                conv_layer=conv_layer, norm_layer=norm_layer, **dd)
        else:
            self.downsample = None

        self.conv1 = conv_layer(in_chs, mid_chs, 1, **dd)
        self.norm1 = norm_layer(mid_chs, **dd)
        self.conv2 = conv_layer(mid_chs, mid_chs, 3, stride=stride, dilation=first_dilation, groups=groups, **dd)
        self.norm2 = norm_layer(mid_chs, **dd)
        self.conv3 = conv_layer(mid_chs, out_chs, 1, **dd)
        self.norm3 = norm_layer(out_chs, apply_act=False, **dd)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)
        self.act3 = get_act_fn(act_layer)

    def zero_init_last(self):
        if getattr(self.norm3, 'scale', None) is not None:
            self.norm3.scale[...] = jnp.zeros_like(self.norm3.scale[...])

    def __call__(self, x):
        shortcut = x
        if self.downsample is not None:
            shortcut = self.downsample(x)
        x = self.conv1(x)
        x = self.norm1(x)
        x = self.conv2(x)
        x = self.norm2(x)
        x = self.conv3(x)
        x = self.norm3(x)
        x = self.drop_path(x)
        return self.act3(x + shortcut)


class DownsampleConv(nnx.Module):
    def __init__(self, in_chs, out_chs, stride=1, dilation=1, first_dilation=None,
                 preact=True, conv_layer=None, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv = conv_layer(in_chs, out_chs, 1, stride=stride, **dd)
        self.norm = None if preact else norm_layer(out_chs, apply_act=False, **dd)

    def __call__(self, x):
        x = self.conv(x)
        return x if self.norm is None else self.norm(x)


class DownsampleAvg(nnx.Module):
    def __init__(self, in_chs, out_chs, stride=1, dilation=1, first_dilation=None,
                 preact=True, conv_layer=None, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.pool_stride = stride if dilation == 1 else 1
        self.do_pool = stride > 1 or dilation > 1
        self.conv = conv_layer(in_chs, out_chs, 1, stride=1, **dd)
        self.norm = None if preact else norm_layer(out_chs, apply_act=False, **dd)

    def __call__(self, x):
        if self.do_pool:
            x = avg_pool2d(x, 2, self.pool_stride, pad_same=True)
        x = self.conv(x)
        return x if self.norm is None else self.norm(x)


class ResNetStage(nnx.Module):
    """One v2 stage (reference resnetv2.py:398-459)."""

    def __init__(self, in_chs, out_chs, stride, dilation, depth, bottle_ratio=0.25,
                 groups=1, avg_down=False, block_dpr=None, block_fn=PreActBottleneck,
                 act_layer=None, conv_layer=None, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs, **block_kwargs):
        self.grad_checkpointing = False
        first_dilation = 1 if dilation in (1, 2) else 2
        layer_kwargs = dict(act_layer=act_layer, conv_layer=conv_layer, norm_layer=norm_layer)
        proj_layer = DownsampleAvg if avg_down else DownsampleConv
        prev_chs = in_chs
        blocks = []
        for block_idx in range(depth):
            drop_path_rate = block_dpr[block_idx] if block_dpr else 0.
            s = stride if block_idx == 0 else 1
            blocks.append(block_fn(
                prev_chs, out_chs, stride=s, dilation=dilation, bottle_ratio=bottle_ratio,
                groups=groups, first_dilation=first_dilation, proj_layer=proj_layer,
                drop_path_rate=drop_path_rate, dtype=dtype, param_dtype=param_dtype,
                rngs=rngs, **layer_kwargs, **block_kwargs))
            prev_chs = out_chs
            first_dilation = dilation
            proj_layer = None
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.grad_checkpointing:
            return checkpoint_seq(self.blocks, x)
        for b in self.blocks:
            x = b(x)
        return x


def is_stem_deep(stem_type: str) -> bool:
    return any(s in stem_type for s in ('deep', 'tiered'))


class Stem(nnx.Module):
    """v2 stem (reference resnetv2.py:473-519 create_resnetv2_stem)."""

    def __init__(self, in_chs, out_chs=64, stem_type='', preact=True,
                 conv_layer=StdConv2d, norm_layer=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs):
        assert stem_type in ('', 'fixed', 'same', 'deep', 'deep_fixed', 'deep_same', 'tiered')
        norm_layer = norm_layer or partial(GroupNormAct, num_groups=32)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.deep = is_stem_deep(stem_type)
        if self.deep:
            stem_chs = (3 * out_chs // 8, out_chs // 2) if 'tiered' in stem_type \
                else (out_chs // 2, out_chs // 2)
            self.conv1 = conv_layer(in_chs, stem_chs[0], kernel_size=3, stride=2, **dd)
            self.norm1 = norm_layer(stem_chs[0], **dd)
            self.conv2 = conv_layer(stem_chs[0], stem_chs[1], kernel_size=3, stride=1, **dd)
            self.norm2 = norm_layer(stem_chs[1], **dd)
            self.conv3 = conv_layer(stem_chs[1], out_chs, kernel_size=3, stride=1, **dd)
            self.norm3 = None if preact else norm_layer(out_chs, **dd)
            self.conv = self.norm = None
        else:
            self.conv = conv_layer(in_chs, out_chs, kernel_size=7, stride=2, **dd)
            self.norm = None if preact else norm_layer(out_chs, **dd)
            self.conv1 = None
        # 'fixed' = BiT zero-pad-1 + VALID 3x3/2 max pool; 'same' = TF-SAME pool
        self.pool_mode = 'fixed' if 'fixed' in stem_type else ('same' if 'same' in stem_type else 'torch')

    def _pool(self, x):
        if self.pool_mode == 'fixed':
            x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
            neg = -jnp.inf if x.dtype == jnp.float32 else jnp.finfo(x.dtype).min
            return jax.lax.reduce_window(
                x, neg, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), 'VALID')
        if self.pool_mode == 'same':
            neg = -jnp.inf if x.dtype == jnp.float32 else jnp.finfo(x.dtype).min
            return jax.lax.reduce_window(
                x, neg, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), 'SAME')
        return max_pool2d(x, 3, 2)

    def __call__(self, x):
        if self.deep:
            x = self.norm1(self.conv1(x))
            x = self.norm2(self.conv2(x))
            x = self.conv3(x)
            if self.norm3 is not None:
                x = self.norm3(x)
        else:
            x = self.conv(x)
            if self.norm is not None:
                x = self.norm(x)
        return self._pool(x)


class ResNetV2(nnx.Module):
    """Pre-activation ResNet (reference resnetv2.py:521-795)."""

    def __init__(
            self,
            layers: Tuple[int, ...],
            channels: Tuple[int, ...] = (256, 512, 1024, 2048),
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: str = 'avg',
            output_stride: int = 32,
            width_factor: int = 1,
            stem_chs: int = 64,
            stem_type: str = '',
            avg_down: bool = False,
            preact: bool = True,
            basic: bool = False,
            bottle_ratio: float = 0.25,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = partial(GroupNormAct, num_groups=32),
            conv_layer: Callable = StdConv2d,
            drop_rate: float = 0.,
            drop_path_rate: float = 0.,
            zero_init_last: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        wf = width_factor
        norm_layer = get_norm_act_layer(norm_layer, act_layer=act_layer)
        dd = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.feature_info = []
        stem_chs = make_divisible(stem_chs * wf)
        self.stem = Stem(in_chans, stem_chs, stem_type, preact,
                         conv_layer=conv_layer, norm_layer=norm_layer, **dd)
        stem_feat = ('stem.conv3' if is_stem_deep(stem_type) else 'stem.conv') if preact else 'stem.norm'
        self.feature_info.append(dict(num_chs=stem_chs, reduction=2, module=stem_feat))

        prev_chs = stem_chs
        curr_stride = 4
        dilation = 1
        block_dprs = calculate_drop_path_rates(drop_path_rate, layers, stagewise=True)
        if preact:
            block_fn = PreActBasic if basic else PreActBottleneck
        else:
            assert not basic
            block_fn = Bottleneck
        stages = []
        for stage_idx, (d, c, bdpr) in enumerate(zip(layers, channels, block_dprs)):
            out_chs = make_divisible(c * wf)
            stride = 1 if stage_idx == 0 else 2
            if curr_stride >= output_stride:
                dilation *= stride
                stride = 1
            stage = ResNetStage(
                prev_chs, out_chs, stride=stride, dilation=dilation, depth=d,
                bottle_ratio=bottle_ratio, avg_down=avg_down, act_layer=act_layer,
                conv_layer=conv_layer, norm_layer=norm_layer, block_dpr=bdpr,
                block_fn=block_fn, **dd)
            prev_chs = out_chs
            curr_stride *= stride
            self.feature_info += [dict(num_chs=prev_chs, reduction=curr_stride, module=f'stages.{stage_idx}')]
            stages.append(stage)
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = prev_chs
        self.norm = norm_layer(self.num_features, **dd) if preact else None
        self.head = ClassifierHead(
            self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate, **dd)

        if zero_init_last:
            for stage in self.stages:
                for b in stage.blocks:
                    if hasattr(b, 'zero_init_last'):
                        b.zero_init_last()

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=r'^stages\.(\d+)' if coarse else [
                (r'^stages\.(\d+)\.blocks\.(\d+)', None),
                (r'^norm', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        if self.norm is not None:
            x = self.norm(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        intermediates = []
        x = self.stem(x)
        if 0 in take_indices:
            intermediates.append(x)
        last_idx = len(self.stages)
        for feat_idx, stage in enumerate(self.stages, start=1):
            if stop_early and feat_idx > max_index:
                break
            x = stage(x)
            if feat_idx in take_indices:
                if feat_idx == last_idx and norm and self.norm is not None:
                    intermediates.append(self.norm(x))
                else:
                    intermediates.append(x)
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages) + 1, indices)
        self.stages = nnx.List(list(self.stages)[:max_index])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Reference layouts map 1:1 after handling the BiT conv head
    (head.fc is a 1x1 Conv2d there, a Linear here)."""
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        if k == 'head.fc.weight' and getattr(v, 'ndim', 0) == 4:
            v = v.reshape(v.shape[0], v.shape[1])  # (N, C, 1, 1) -> (N, C)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_resnetv2(variant: str, pretrained: bool = False, **kwargs) -> ResNetV2:
    return build_model_with_cfg(
        ResNetV2, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _create_resnetv2_bit(variant: str, pretrained: bool = False, **kwargs) -> ResNetV2:
    return _create_resnetv2(
        variant, pretrained=pretrained, stem_type='fixed',
        conv_layer=partial(StdConv2d, eps=1e-8), **kwargs)


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bilinear',
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'stem.conv',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'resnetv2_50x1_bit.goog_in21k_ft_in1k': _cfg(),
    'resnetv2_50x3_bit.goog_in21k_ft_in1k': _cfg(),
    'resnetv2_101x1_bit.goog_in21k_ft_in1k': _cfg(),
    'resnetv2_101x3_bit.goog_in21k_ft_in1k': _cfg(),
    'resnetv2_152x2_bit.goog_in21k_ft_in1k': _cfg(),
    'resnetv2_152x4_bit.goog_in21k_ft_in1k': _cfg(input_size=(3, 480, 480), pool_size=(15, 15)),
    'resnetv2_18.ra4_e3600_r224_in1k': _cfg(
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), interpolation='bicubic'),
    'resnetv2_18d.untrained': _cfg(
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
        interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_34.ra4_e3600_r224_in1k': _cfg(
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), interpolation='bicubic'),
    'resnetv2_34d.ra4_e3600_r224_in1k': _cfg(
        mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
        interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_50.a1h_in1k': _cfg(interpolation='bicubic', crop_pct=0.95),
    'resnetv2_50d.untrained': _cfg(interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_50t.untrained': _cfg(interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_101.a1h_in1k': _cfg(interpolation='bicubic', crop_pct=0.95),
    'resnetv2_101d.untrained': _cfg(interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_152.untrained': _cfg(interpolation='bicubic'),
    'resnetv2_152d.untrained': _cfg(interpolation='bicubic', first_conv='stem.conv1'),
    'resnetv2_50d_gn.ah_in1k': _cfg(
        interpolation='bicubic', first_conv='stem.conv1', crop_pct=0.95),
    'resnetv2_50d_evos.ah_in1k': _cfg(
        interpolation='bicubic', first_conv='stem.conv1', crop_pct=0.95),
    'resnetv2_50d_frn.untrained': _cfg(interpolation='bicubic', first_conv='stem.conv1'),
})


@register_model
def resnetv2_50x1_bit(pretrained=False, **kwargs) -> ResNetV2:
    """Big Transfer (BiT) ResNetV2-50x1."""
    return _create_resnetv2_bit(
        'resnetv2_50x1_bit', pretrained=pretrained, layers=(3, 4, 6, 3), width_factor=1, **kwargs)


@register_model
def resnetv2_50x3_bit(pretrained=False, **kwargs) -> ResNetV2:
    return _create_resnetv2_bit(
        'resnetv2_50x3_bit', pretrained=pretrained, layers=(3, 4, 6, 3), width_factor=3, **kwargs)


@register_model
def resnetv2_101x1_bit(pretrained=False, **kwargs) -> ResNetV2:
    return _create_resnetv2_bit(
        'resnetv2_101x1_bit', pretrained=pretrained, layers=(3, 4, 23, 3), width_factor=1, **kwargs)


@register_model
def resnetv2_101x3_bit(pretrained=False, **kwargs) -> ResNetV2:
    return _create_resnetv2_bit(
        'resnetv2_101x3_bit', pretrained=pretrained, layers=(3, 4, 23, 3), width_factor=3, **kwargs)


@register_model
def resnetv2_152x2_bit(pretrained=False, **kwargs) -> ResNetV2:
    return _create_resnetv2_bit(
        'resnetv2_152x2_bit', pretrained=pretrained, layers=(3, 8, 36, 3), width_factor=2, **kwargs)


@register_model
def resnetv2_152x4_bit(pretrained=False, **kwargs) -> ResNetV2:
    return _create_resnetv2_bit(
        'resnetv2_152x4_bit', pretrained=pretrained, layers=(3, 8, 36, 3), width_factor=4, **kwargs)


@register_model
def resnetv2_18(pretrained=False, **kwargs) -> ResNetV2:
    """Pre-act ResNet-18 with plain conv + BN."""
    model_args = dict(
        layers=(2, 2, 2, 2), channels=(64, 128, 256, 512), basic=True, bottle_ratio=1.0,
        conv_layer=create_conv2d, norm_layer=BatchNormAct2d)
    return _create_resnetv2('resnetv2_18', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_18d(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(2, 2, 2, 2), channels=(64, 128, 256, 512), basic=True, bottle_ratio=1.0,
        conv_layer=create_conv2d, norm_layer=BatchNormAct2d, stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_18d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_34(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 4, 6, 3), channels=(64, 128, 256, 512), basic=True, bottle_ratio=1.0,
        conv_layer=create_conv2d, norm_layer=BatchNormAct2d)
    return _create_resnetv2('resnetv2_34', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_34d(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 4, 6, 3), channels=(64, 128, 256, 512), basic=True, bottle_ratio=1.0,
        conv_layer=create_conv2d, norm_layer=BatchNormAct2d, stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_34d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d)
    return _create_resnetv2('resnetv2_50', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50d(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_50d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50t(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d,
        stem_type='tiered', avg_down=True)
    return _create_resnetv2('resnetv2_50t', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_101(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(layers=(3, 4, 23, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d)
    return _create_resnetv2('resnetv2_101', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_101d(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 4, 23, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_101d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_152(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(layers=(3, 8, 36, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d)
    return _create_resnetv2('resnetv2_152', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_152d(pretrained=False, **kwargs) -> ResNetV2:
    model_args = dict(
        layers=(3, 8, 36, 3), conv_layer=create_conv2d, norm_layer=BatchNormAct2d,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_152d', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50d_gn(pretrained=False, **kwargs) -> ResNetV2:
    """Pre-act ResNet-50d with GroupNorm."""
    model_args = dict(
        layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=GroupNormAct,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_50d_gn', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50d_evos(pretrained=False, **kwargs) -> ResNetV2:
    """Pre-act ResNet-50d with EvoNorm-S0."""
    model_args = dict(
        layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=EvoNorm2dS0,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_50d_evos', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def resnetv2_50d_frn(pretrained=False, **kwargs) -> ResNetV2:
    """Pre-act ResNet-50d with Filter Response Norm + TLU."""
    model_args = dict(
        layers=(3, 4, 6, 3), conv_layer=create_conv2d, norm_layer=FilterResponseNormTlu2d,
        stem_type='deep', avg_down=True)
    return _create_resnetv2('resnetv2_50d_frn', pretrained=pretrained, **dict(model_args, **kwargs))
