"""Pooling-based Vision Transformer (PiT)
(reference: timm/models/pit.py:1-555), TPU-native NHWC/NLC.

ViT stages separated by depthwise-conv token pooling; the cls (and optional
distill) tokens ride along through a parallel linear. Spatial maps stay NHWC;
transformer blocks reuse the ViT Block on NLC tokens.
"""
from __future__ import annotations

import math
import re
from functools import partial
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import LayerNorm, calculate_drop_path_rates, create_conv2d, to_2tuple, trunc_normal_, zeros_
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .vision_transformer import Block

__all__ = ['PoolingVisionTransformer']


class PitPooling(nnx.Module):
    """dw conv pool for spatial tokens + fc for cls tokens (reference pit.py:76-100)."""

    def __init__(self, in_feature, out_feature, stride, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = create_conv2d(
            in_feature, out_feature, stride + 1, stride=stride, padding=stride // 2,
            groups=in_feature, bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc = nnx.Linear(
            in_feature, out_feature, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x, cls_token):
        return self.conv(x), self.fc(cls_token)


class PitTransformer(nnx.Module):
    """A stage: optional pooling then ViT blocks over [cls; spatial] tokens
    (reference pit.py:28-74)."""

    def __init__(self, base_dim, depth, heads, mlp_ratio, pool=None,
                 proj_drop=0.0, attn_drop=0.0, drop_path_prob=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        embed_dim = base_dim * heads
        self.pool = pool
        self.blocks = nnx.List([
            Block(
                dim=embed_dim,
                num_heads=heads,
                mlp_ratio=mlp_ratio,
                qkv_bias=True,
                proj_drop=proj_drop,
                attn_drop=attn_drop,
                drop_path=drop_path_prob[i] if drop_path_prob is not None else 0.0,
                norm_layer=partial(LayerNorm, eps=1e-6),
                dtype=dtype, param_dtype=param_dtype, rngs=rngs,
            )
            for i in range(depth)])

    def __call__(self, x, cls_tokens):
        token_length = cls_tokens.shape[1]
        if self.pool is not None:
            x, cls_tokens = self.pool(x, cls_tokens)
        B, H, W, C = x.shape
        tokens = jnp.concatenate([cls_tokens, x.reshape(B, -1, C)], axis=1)
        for blk in self.blocks:
            tokens = blk(tokens)
        cls_tokens = tokens[:, :token_length]
        x = tokens[:, token_length:].reshape(B, H, W, C)
        return x, cls_tokens


class ConvEmbedding(nnx.Module):
    """(reference pit.py:102-135)."""

    def __init__(self, in_channels, out_channels, img_size=224, patch_size=16, stride=8,
                 padding=0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.img_size = to_2tuple(img_size)
        self.patch_size = to_2tuple(patch_size)
        self.height = math.floor((self.img_size[0] + 2 * padding - self.patch_size[0]) / stride + 1)
        self.width = math.floor((self.img_size[1] + 2 * padding - self.patch_size[1]) / stride + 1)
        self.grid_size = (self.height, self.width)
        self.conv = create_conv2d(
            in_channels, out_channels, patch_size, stride=stride, padding=padding, bias=True,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.conv(x)


class PoolingVisionTransformer(nnx.Module):
    """(reference pit.py:137-360)."""

    def __init__(
            self,
            img_size: int = 224,
            patch_size: int = 16,
            stride: int = 8,
            stem_type: str = 'overlap',
            base_dims: Sequence[int] = (48, 48, 48),
            depth: Sequence[int] = (2, 6, 4),
            heads: Sequence[int] = (2, 4, 8),
            mlp_ratio: float = 4,
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: str = 'token',
            distilled: bool = False,
            drop_rate: float = 0.0,
            pos_drop_drate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('token',)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.base_dims = base_dims
        self.heads = heads
        embed_dim = base_dims[0] * heads[0]
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_tokens = 2 if distilled else 1
        self.feature_info = []

        self.patch_embed = ConvEmbedding(in_chans, embed_dim, img_size, patch_size, stride, **kw)
        import jax
        k1, k2 = jax.random.split(rngs.params())
        # NHWC pos embed (the reference stores NCHW; the filter transposes)
        self.pos_embed = nnx.Param(trunc_normal_(std=0.02)(
            k1, (1, self.patch_embed.height, self.patch_embed.width, embed_dim), param_dtype))
        self.cls_token = nnx.Param(trunc_normal_(std=0.02)(
            k2, (1, self.num_tokens, embed_dim), param_dtype))
        self.pos_drop = Dropout(pos_drop_drate, rngs=rngs)

        transformers = []
        dpr = calculate_drop_path_rates(drop_path_rate, list(depth), stagewise=True)
        prev_dim = embed_dim
        for i in range(len(depth)):
            pool = None
            embed_dim = base_dims[i] * heads[i]
            if i > 0:
                pool = PitPooling(prev_dim, embed_dim, stride=2, **kw)
            transformers.append(PitTransformer(
                base_dims[i], depth[i], heads[i], mlp_ratio, pool=pool,
                proj_drop=proj_drop_rate, attn_drop=attn_drop_rate, drop_path_prob=dpr[i], **kw))
            prev_dim = embed_dim
            self.feature_info += [dict(num_chs=prev_dim, reduction=(stride - 1) * 2 ** i, module=f'transformers.{i}')]
        self.transformers = nnx.List(transformers)

        self.norm = LayerNorm(base_dims[-1] * heads[-1], eps=1e-6, rngs=rngs)
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        linear = partial(nnx.Linear, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, **kw)
        self.head = linear(self.embed_dim, num_classes) if num_classes > 0 else None
        self.head_dist = (linear(self.embed_dim, num_classes) if num_classes > 0 else None) if distilled else None
        self.distilled_training = False
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token'}

    def set_distilled_training(self, enable: bool = True):
        self.distilled_training = enable

    def set_grad_checkpointing(self, enable: bool = True):
        assert not enable, 'gradient checkpointing not supported'

    def get_classifier(self):
        if self.head_dist is not None:
            return self.head, self.head_dist
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        linear = partial(nnx.Linear, kernel_init=trunc_normal_(std=0.02),
                         dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)
        self.head = linear(self.embed_dim, num_classes) if num_classes > 0 else None
        if self.head_dist is not None:
            self.head_dist = linear(self.embed_dim, num_classes) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        x = self.pos_drop(x + self.pos_embed[...].astype(x.dtype))
        cls_tokens = jnp.broadcast_to(
            self.cls_token[...].astype(x.dtype), (x.shape[0], self.num_tokens, x.shape[-1]))
        for stage in self.transformers:
            x, cls_tokens = stage(x, cls_tokens)
        return self.norm(cls_tokens)

    def forward_head(self, x, pre_logits: bool = False):
        if self.head_dist is not None:
            assert self.global_pool == 'token'
            x, x_dist = x[:, 0], x[:, 1]
            x = self.head_drop(x)
            x_dist = self.head_drop(x_dist)
            if not pre_logits:
                x = self.head(x)
                x_dist = self.head_dist(x_dist)
            if self.distilled_training and not self.head_drop.deterministic:
                return x, x_dist
            return (x + x_dist) / 2
        if self.global_pool == 'token':
            x = x[:, 0]
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.transformers), indices)
        x = self.patch_embed(x)
        x = self.pos_drop(x + self.pos_embed[...].astype(x.dtype))
        cls_tokens = jnp.broadcast_to(
            self.cls_token[...].astype(x.dtype), (x.shape[0], self.num_tokens, x.shape[-1]))
        intermediates = []
        last_idx = len(self.transformers) - 1
        stages = self.transformers if not stop_early else list(self.transformers)[:max_index + 1]
        feat_idx = 0
        for feat_idx, stage in enumerate(stages):
            x, cls_tokens = stage(x, cls_tokens)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        if feat_idx == last_idx:
            cls_tokens = self.norm(cls_tokens)
        return cls_tokens, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.transformers), indices)
        self.transformers = nnx.List(list(self.transformers)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0)
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Remap original pools.N → transformers.N+1.pool, transpose the NCHW
    pos_embed to NHWC (reference pit.py:363-372)."""
    import numpy as np

    from ._torch_convert import convert_torch_state_dict
    p_blocks = re.compile(r'pools\.(\d)\.')
    out = {}
    for k, v in state_dict.items():
        k = p_blocks.sub(lambda exp: f'transformers.{int(exp.group(1)) + 1}.pool.', k)
        if k == 'pos_embed':
            v = np.asarray(v).transpose(0, 2, 3, 1)  # NCHW → NHWC
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_pit(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        PoolingVisionTransformer, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': 0.9, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.conv', 'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'pit_ti_224.in1k': _cfg(hf_hub_id='timm/'),
    'pit_xs_224.in1k': _cfg(hf_hub_id='timm/'),
    'pit_s_224.in1k': _cfg(hf_hub_id='timm/'),
    'pit_b_224.in1k': _cfg(hf_hub_id='timm/'),
    'pit_ti_distilled_224.in1k': _cfg(hf_hub_id='timm/', classifier=('head', 'head_dist')),
    'pit_xs_distilled_224.in1k': _cfg(hf_hub_id='timm/', classifier=('head', 'head_dist')),
    'pit_s_distilled_224.in1k': _cfg(hf_hub_id='timm/', classifier=('head', 'head_dist')),
    'pit_b_distilled_224.in1k': _cfg(hf_hub_id='timm/', classifier=('head', 'head_dist')),
})


@register_model
def pit_b_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=14, stride=7, base_dims=[64, 64, 64], depth=[3, 6, 4], heads=[4, 8, 16], mlp_ratio=4)
    return _create_pit('pit_b_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_s_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[48, 48, 48], depth=[2, 6, 4], heads=[3, 6, 12], mlp_ratio=4)
    return _create_pit('pit_s_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_xs_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[48, 48, 48], depth=[2, 6, 4], heads=[2, 4, 8], mlp_ratio=4)
    return _create_pit('pit_xs_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_ti_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[32, 32, 32], depth=[2, 6, 4], heads=[2, 4, 8], mlp_ratio=4)
    return _create_pit('pit_ti_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_b_distilled_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=14, stride=7, base_dims=[64, 64, 64], depth=[3, 6, 4], heads=[4, 8, 16],
        mlp_ratio=4, distilled=True)
    return _create_pit('pit_b_distilled_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_s_distilled_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[48, 48, 48], depth=[2, 6, 4], heads=[3, 6, 12],
        mlp_ratio=4, distilled=True)
    return _create_pit('pit_s_distilled_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_xs_distilled_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[48, 48, 48], depth=[2, 6, 4], heads=[2, 4, 8],
        mlp_ratio=4, distilled=True)
    return _create_pit('pit_xs_distilled_224', pretrained, **dict(model_args, **kwargs))


@register_model
def pit_ti_distilled_224(pretrained=False, **kwargs) -> PoolingVisionTransformer:
    model_args = dict(
        patch_size=16, stride=8, base_dims=[32, 32, 32], depth=[2, 6, 4], heads=[2, 4, 8],
        mlp_ratio=4, distilled=True)
    return _create_pit('pit_ti_distilled_224', pretrained, **dict(model_args, **kwargs))
