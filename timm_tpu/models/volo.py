"""VOLO: Vision Outlooker, TPU-native
(reference: timm/models/volo.py:1-1460; Yuan et al. 2021).

Outlook attention predicts per-window k×k→k×k mixing weights from pooled
features and applies them to unfolded value windows, then folds overlapping
results back. TPU-first notes: torch's `nn.Unfold`/`F.fold` (im2col + its
scatter-add adjoint) are replaced by k² static shifted SLICES (unfold) and k²
static `.at[].add` updates (fold) — fixed-shape ops XLA fuses into the
attention einsums, no gather/scatter with dynamic indices. k=3 everywhere in
published configs, so this is 9 slices each way.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, DropPath, Dropout, LayerNorm, Mlp, to_2tuple, to_ntuple,
    trunc_normal_, zeros_,
)
from ..layers.attention import scaled_dot_product_attention
from ..layers.drop import dropout_rng_key
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['VOLO', 'OutlookAttention', 'Outlooker']


def _unfold_nhwc(v, kernel_size: int, padding: int, stride: int):
    """(B, H, W, C) → (B, h, w, k*k, C) of overlapping windows via static
    shifted slices (torch nn.Unfold equivalent, NHWC)."""
    B, H, W, C = v.shape
    h = (H + 2 * padding - kernel_size) // stride + 1
    w = (W + 2 * padding - kernel_size) // stride + 1
    vp = jnp.pad(v, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    patches = []
    for i in range(kernel_size):
        for j in range(kernel_size):
            patches.append(vp[:, i:i + stride * (h - 1) + 1:stride, j:j + stride * (w - 1) + 1:stride, :])
    return jnp.stack(patches, axis=3)  # (B, h, w, k*k, C)


def _fold_nhwc(y, out_size: Tuple[int, int], kernel_size: int, padding: int, stride: int):
    """(B, h, w, k*k, C) → (B, H, W, C) summing overlapping windows
    (torch F.fold equivalent, NHWC)."""
    B, h, w, kk, C = y.shape
    H, W = out_size
    out = jnp.zeros((B, H + 2 * padding, W + 2 * padding, C), y.dtype)
    idx = 0
    for i in range(kernel_size):
        for j in range(kernel_size):
            out = out.at[:, i:i + stride * (h - 1) + 1:stride, j:j + stride * (w - 1) + 1:stride, :].add(
                y[:, :, :, idx, :])
            idx += 1
    return out[:, padding:padding + H, padding:padding + W, :]


class OutlookAttention(nnx.Module):
    """Outlook attention (reference volo.py:39-119)."""

    def __init__(self, dim: int, num_heads: int, kernel_size: int = 3, padding: int = 1,
                 stride: int = 1, qkv_bias: bool = False, attn_drop: float = 0.0,
                 proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.kernel_size = kernel_size
        self.padding = padding
        self.stride = stride
        head_dim = dim // num_heads
        self.scale = head_dim ** -0.5
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.v = linear(dim, dim, use_bias=qkv_bias)
        self.attn = linear(dim, kernel_size ** 4 * num_heads)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        k = self.kernel_size
        nh = self.num_heads
        d = C // nh
        h, w = math.ceil(H / self.stride), math.ceil(W / self.stride)

        v = self.v(x)  # (B, H, W, C)
        v = _unfold_nhwc(v, k, self.padding, self.stride)  # (B, h, w, k*k, C)
        v = v.reshape(B, h * w, k * k, nh, d).transpose(0, 3, 1, 2, 4)  # (B, nh, N, kk, d)

        # attention weights from stride-pooled features (ceil-mode avg pool:
        # zero-pad to a stride multiple, sum, divide by VALID element count)
        if self.stride > 1:
            ph, pw = h * self.stride - H, w * self.stride - W
            xp = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
            sums = xp.reshape(B, h, self.stride, w, self.stride, C).sum(axis=(2, 4))
            cnt_h = jnp.minimum(jnp.arange(h) * self.stride + self.stride, H) - jnp.arange(h) * self.stride
            cnt_w = jnp.minimum(jnp.arange(w) * self.stride + self.stride, W) - jnp.arange(w) * self.stride
            counts = (cnt_h[:, None] * cnt_w[None, :]).astype(x.dtype)
            pooled = sums / counts[None, :, :, None]
        else:
            pooled = x
        attn = self.attn(pooled).reshape(B, h * w, nh, k * k, k * k).transpose(0, 2, 1, 3, 4)
        attn = attn * self.scale
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)

        y = jnp.einsum('bhnpq,bhnqd->bhnpd', attn, v)  # (B, nh, N, kk, d)
        y = y.transpose(0, 2, 3, 1, 4).reshape(B, h, w, k * k, C)
        x = _fold_nhwc(y, (H, W), k, self.padding, self.stride)
        x = self.proj(x)
        return self.proj_drop(x)


class Outlooker(nnx.Module):
    """Outlook attention block (reference volo.py:121-191)."""

    def __init__(self, dim: int, kernel_size: int, padding: int, stride: int = 1,
                 num_heads: int = 1, mlp_ratio: float = 3.0, attn_drop: float = 0.0,
                 drop_path: float = 0.0, act_layer: Union[str, Callable] = 'gelu',
                 norm_layer: Callable = LayerNorm, qkv_bias: bool = False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = OutlookAttention(
            dim, num_heads, kernel_size=kernel_size, padding=padding, stride=stride,
            qkv_bias=qkv_bias, attn_drop=attn_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = x + self.drop_path1(self.attn(self.norm1(x)))
        x = x + self.drop_path2(self.mlp(self.norm2(x)))
        return x


class VoloAttention(nnx.Module):
    """Standard MHSA over an NHWC grid (reference volo.py:193-258)."""

    def __init__(self, dim: int, num_heads: int = 8, qkv_bias: bool = False,
                 attn_drop: float = 0.0, proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        N = H * W
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale, fused=False)
        x = x.transpose(0, 2, 1, 3).reshape(B, H, W, C)
        x = self.proj(x)
        return self.proj_drop(x)


class Transformer(nnx.Module):
    """Transformer block on NHWC grid (reference volo.py:261-311)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float = 4.0, qkv_bias: bool = False,
                 attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = VoloAttention(dim, num_heads=num_heads, qkv_bias=qkv_bias, attn_drop=attn_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = x + self.drop_path1(self.attn(self.norm1(x)))
        x = x + self.drop_path2(self.mlp(self.norm2(x)))
        return x


class ClassAttention(nnx.Module):
    """VOLO class attention w/ fused kv (reference volo.py:313-376)."""

    def __init__(self, dim: int, num_heads: int = 8, head_dim: Optional[int] = None,
                 qkv_bias: bool = False, attn_drop: float = 0.0, proj_drop: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.head_dim = head_dim if head_dim is not None else dim // num_heads
        self.scale = self.head_dim ** -0.5
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.kv = linear(dim, self.head_dim * num_heads * 2, use_bias=qkv_bias)
        self.q = linear(dim, self.head_dim * num_heads, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(self.head_dim * num_heads, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        kv = self.kv(x).reshape(B, N, 2, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        k, v = kv[0], kv[1]
        q = self.q(x[:, 0:1]).reshape(B, 1, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        cls_embed = scaled_dot_product_attention(
            q, k, v, dropout_p=dropout_p, dropout_key=dropout_key, scale=self.scale, fused=False)
        cls_embed = cls_embed.transpose(0, 2, 1, 3).reshape(B, 1, self.head_dim * self.num_heads)
        cls_embed = self.proj(cls_embed)
        return self.proj_drop(cls_embed)


class ClassBlock(nnx.Module):
    """Class-attention block updating only the cls token (reference volo.py:378-443)."""

    def __init__(self, dim: int, num_heads: int, head_dim: Optional[int] = None,
                 mlp_ratio: float = 4.0, qkv_bias: bool = False, drop: float = 0.0,
                 attn_drop: float = 0.0, drop_path: float = 0.0,
                 act_layer: Union[str, Callable] = 'gelu', norm_layer: Callable = LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = ClassAttention(
            dim, num_heads=num_heads, head_dim=head_dim, qkv_bias=qkv_bias,
            attn_drop=attn_drop, proj_drop=drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer, drop=drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        cls_embed = x[:, :1]
        cls_embed = cls_embed + self.drop_path1(self.attn(self.norm1(x)))
        cls_embed = cls_embed + self.drop_path2(self.mlp(self.norm2(cls_embed)))
        return jnp.concatenate([cls_embed, x[:, 1:]], axis=1)


class _StemConvBnRelu(nnx.Module):
    def __init__(self, in_chs, out_chs, kernel_size, stride, padding,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(kernel_size, kernel_size), strides=stride,
            padding=[(padding, padding), (padding, padding)], use_bias=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_chs, rngs=rngs)

    def __call__(self, x):
        return nnx.relu(self.bn(self.conv(x)))


class VoloPatchEmbed(nnx.Module):
    """Multi-conv stem + strided patch projection (reference volo.py:498-566)."""

    def __init__(self, img_size: int = 224, stem_conv: bool = False, stem_stride: int = 1,
                 patch_size: int = 8, in_chans: int = 3, hidden_dim: int = 64,
                 embed_dim: int = 384,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert patch_size in (4, 8, 16)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        if stem_conv:
            self.convs = nnx.List([
                _StemConvBnRelu(in_chans, hidden_dim, 7, stem_stride, 3, **kw),
                _StemConvBnRelu(hidden_dim, hidden_dim, 3, 1, 1, **kw),
                _StemConvBnRelu(hidden_dim, hidden_dim, 3, 1, 1, **kw),
            ])
        else:
            self.convs = None
        ps = patch_size // stem_stride
        self.proj = nnx.Conv(
            hidden_dim if stem_conv else in_chans, embed_dim, kernel_size=(ps, ps), strides=ps,
            padding='VALID', dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_patches = (img_size // patch_size) ** 2

    def __call__(self, x):
        if self.convs is not None:
            for c in self.convs:
                x = c(x)
        return self.proj(x)  # (B, H', W', embed_dim)


class Downsample(nnx.Module):
    """Strided-conv downsample between stages (reference volo.py:568-603)."""

    def __init__(self, in_embed_dim: int, out_embed_dim: int, patch_size: int = 2,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.proj = nnx.Conv(
            in_embed_dim, out_embed_dim, kernel_size=(patch_size, patch_size),
            strides=patch_size, padding='VALID',
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.proj(x)


class VOLO(nnx.Module):
    """VOLO with the reference's model contract (reference volo.py:708-1213).

    `use_mix_token` training (token-labeling bbox mixing, reference
    forward_train) is not implemented; standard classification fwd only.
    """

    def __init__(
            self,
            layers: Tuple[int, ...],
            img_size: int = 224,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'token',
            patch_size: int = 8,
            stem_hidden_dim: int = 64,
            embed_dims: Optional[Tuple[int, ...]] = None,
            num_heads: Optional[Tuple[int, ...]] = None,
            downsamples: Tuple[bool, ...] = (True, False, False, False),
            outlook_attention: Tuple[bool, ...] = (True, False, False, False),
            mlp_ratio: float = 3.0,
            qkv_bias: bool = False,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            norm_layer: Optional[Callable] = None,
            post_layers: Optional[Tuple[str, ...]] = ('ca', 'ca'),
            use_aux_head: bool = True,
            pooling_scale: int = 2,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # reference uses torch nn.LayerNorm default eps (1e-5)
        norm_layer = norm_layer or partial(LayerNorm, eps=1e-5)
        num_layers = len(layers)
        mlp_ratio = to_ntuple(num_layers)(mlp_ratio)
        img_size = to_2tuple(img_size)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.pooling_scale = pooling_scale
        self.num_features = self.head_hidden_size = embed_dims[-1]
        self.grad_checkpointing = False
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.patch_embed = VoloPatchEmbed(
            img_size=img_size[0], stem_conv=True, stem_stride=2, patch_size=patch_size,
            in_chans=in_chans, hidden_dim=stem_hidden_dim, embed_dim=embed_dims[0], **kw)
        r = patch_size

        patch_grid = (img_size[0] // patch_size // pooling_scale, img_size[1] // patch_size // pooling_scale)
        self.pos_embed = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, patch_grid[0], patch_grid[1], embed_dims[-1]), param_dtype))
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        self.stage_ends = []
        self.feature_info = []
        network = []
        block_idx = 0
        total = sum(layers)
        for i in range(num_layers):
            blocks = []
            for bi in range(layers[i]):
                dpr = drop_path_rate * (bi + sum(layers[:i])) / max(total - 1, 1)
                if outlook_attention[i]:
                    blocks.append(Outlooker(
                        embed_dims[i], kernel_size=3, padding=1, stride=2,
                        num_heads=num_heads[i], mlp_ratio=mlp_ratio[i], qkv_bias=qkv_bias,
                        attn_drop=attn_drop_rate, drop_path=dpr, norm_layer=norm_layer, **kw))
                else:
                    blocks.append(Transformer(
                        embed_dims[i], num_heads[i], mlp_ratio=mlp_ratio[i], qkv_bias=qkv_bias,
                        attn_drop=attn_drop_rate, drop_path=dpr, norm_layer=norm_layer, **kw))
            network.append(nnx.List(blocks))
            self.stage_ends.append(block_idx)
            self.feature_info.append(dict(num_chs=embed_dims[i], reduction=r, module=f'network.{block_idx}'))
            block_idx += 1
            if downsamples[i]:
                network.append(Downsample(embed_dims[i], embed_dims[i + 1], 2, **kw))
                r *= 2
                block_idx += 1
        self.network = nnx.List(network)

        if post_layers is not None:
            assert all(p == 'ca' for p in post_layers)
            self.post_network = nnx.List([
                ClassBlock(
                    dim=embed_dims[-1], num_heads=num_heads[-1], mlp_ratio=mlp_ratio[-1],
                    qkv_bias=qkv_bias, attn_drop=attn_drop_rate, norm_layer=norm_layer, **kw)
                for _ in post_layers
            ])
            self.cls_token = nnx.Param(
                trunc_normal_(std=0.02)(rngs.params(), (1, 1, embed_dims[-1]), param_dtype))
        else:
            self.post_network = None
            self.cls_token = None

        if use_aux_head:
            self.aux_head = nnx.Linear(
                self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        else:
            self.aux_head = None
        self.norm = norm_layer(self.num_features, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed',
            blocks=[(r'^network\.(\d+)\.(\d+)', None), (r'^network\.(\d+)', (0,))],
            blocks2=[(r'^cls_token', (0,)), (r'^post_network\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        mk = lambda: nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)
        self.head = mk() if num_classes > 0 else None
        if self.aux_head is not None:
            self.aux_head = mk() if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_tokens(self, x):
        from ._manipulate import checkpoint_seq
        for idx, block in enumerate(self.network):
            if idx == 2:  # pos embed after the outlooker stage + downsample
                x = x + self.pos_embed[...].astype(x.dtype)
                x = self.pos_drop(x)
            if isinstance(block, nnx.List):
                if self.grad_checkpointing:
                    x = checkpoint_seq(block, x)
                else:
                    for blk in block:
                        x = blk(x)
            else:
                x = block(x)
        B, H, W, C = x.shape
        return x.reshape(B, -1, C)

    def forward_cls(self, x):
        B = x.shape[0]
        cls_tokens = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls_tokens, x], axis=1)
        for block in self.post_network:
            x = block(x)
        return x

    def forward_features(self, x):
        x = self.patch_embed(x)
        x = self.forward_tokens(x)
        if self.post_network is not None:
            x = self.forward_cls(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            out = x.mean(axis=1)
        elif self.global_pool == 'token':
            out = x[:, 0]
        else:
            out = x
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return out
        out = self.head(out)
        if self.aux_head is not None:
            aux = self.aux_head(x[:, 1:])
            out = out + 0.5 * aux.max(axis=1)
        return out

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stage_ends), indices)
        take_blocks = {self.stage_ends[i]: i for i in take_indices}
        max_block = self.stage_ends[max_index]

        x = self.patch_embed(x)
        intermediates = []
        for idx, block in enumerate(self.network):
            if stop_early and idx > max_block:
                break
            if idx == 2:
                x = x + self.pos_embed[...].astype(x.dtype)
                x = self.pos_drop(x)
            if isinstance(block, nnx.List):
                for blk in block:
                    x = blk(x)
            else:
                x = block(x)
            if idx in take_blocks:
                intermediates.append(x)
        if intermediates_only:
            return intermediates

        B, H, W, C = x.shape
        x = x.reshape(B, -1, C)
        if self.post_network is not None:
            x = self.forward_cls(x)
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stage_ends), indices)
        max_block = self.stage_ends[max_index]
        self.network = nnx.List(list(self.network)[:max_block + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            if self.post_network is not None:
                self.post_network = nnx.List([])
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    import re
    out = {}
    for k, v in state_dict.items():
        # torch stem Sequential conv.{0,1,3,4,6,7} → convs.{i}.{conv,bn}
        m = re.match(r'^patch_embed\.conv\.(\d+)\.(.*)$', k)
        if m:
            i = int(m.group(1))
            stage, part = divmod(i, 3)
            name = 'conv' if part == 0 else 'bn'
            k = f'patch_embed.convs.{stage}.{name}.{m.group(2)}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_volo(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        VOLO, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.96,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.convs.0.conv',
        'classifier': ('head', 'aux_head'),
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'volo_d1_224.sail_in1k': _cfg(hf_hub_id='timm/'),
    'volo_d1_384.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, input_size=(3, 384, 384)),
    'volo_d2_224.sail_in1k': _cfg(hf_hub_id='timm/'),
    'volo_d2_384.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, input_size=(3, 384, 384)),
    'volo_d3_224.sail_in1k': _cfg(hf_hub_id='timm/'),
    'volo_d3_448.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.0, input_size=(3, 448, 448)),
    'volo_d4_224.sail_in1k': _cfg(hf_hub_id='timm/'),
    'volo_d4_448.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.15, input_size=(3, 448, 448)),
    'volo_d5_224.sail_in1k': _cfg(hf_hub_id='timm/'),
    'volo_d5_448.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.15, input_size=(3, 448, 448)),
    'volo_d5_512.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=1.15, input_size=(3, 512, 512)),
    'test_volo.untrained': _cfg(input_size=(3, 96, 96)),
})


@register_model
def volo_d1_224(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(layers=(4, 4, 8, 2), embed_dims=(192, 384, 384, 384), num_heads=(6, 12, 12, 12))
    return _create_volo('volo_d1_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d1_384(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(img_size=384, layers=(4, 4, 8, 2), embed_dims=(192, 384, 384, 384), num_heads=(6, 12, 12, 12))
    return _create_volo('volo_d1_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d2_224(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(layers=(6, 4, 10, 4), embed_dims=(256, 512, 512, 512), num_heads=(8, 16, 16, 16))
    return _create_volo('volo_d2_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d2_384(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(img_size=384, layers=(6, 4, 10, 4), embed_dims=(256, 512, 512, 512), num_heads=(8, 16, 16, 16))
    return _create_volo('volo_d2_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d3_224(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(layers=(8, 8, 16, 4), embed_dims=(256, 512, 512, 512), num_heads=(8, 16, 16, 16))
    return _create_volo('volo_d3_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d3_448(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(img_size=448, layers=(8, 8, 16, 4), embed_dims=(256, 512, 512, 512), num_heads=(8, 16, 16, 16))
    return _create_volo('volo_d3_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d4_224(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(layers=(8, 8, 16, 4), embed_dims=(384, 768, 768, 768), num_heads=(12, 16, 16, 16))
    return _create_volo('volo_d4_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d4_448(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(img_size=448, layers=(8, 8, 16, 4), embed_dims=(384, 768, 768, 768), num_heads=(12, 16, 16, 16))
    return _create_volo('volo_d4_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d5_224(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(
        layers=(12, 12, 20, 4), embed_dims=(384, 768, 768, 768), num_heads=(12, 16, 16, 16),
        mlp_ratio=4, stem_hidden_dim=128)
    return _create_volo('volo_d5_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d5_448(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(
        img_size=448, layers=(12, 12, 20, 4), embed_dims=(384, 768, 768, 768), num_heads=(12, 16, 16, 16),
        mlp_ratio=4, stem_hidden_dim=128)
    return _create_volo('volo_d5_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def volo_d5_512(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(
        img_size=512, layers=(12, 12, 20, 4), embed_dims=(384, 768, 768, 768), num_heads=(12, 16, 16, 16),
        mlp_ratio=4, stem_hidden_dim=128)
    return _create_volo('volo_d5_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_volo(pretrained=False, **kwargs) -> VOLO:
    model_args = dict(
        img_size=96, patch_size=8, layers=(1, 1, 1), embed_dims=(32, 64, 64), num_heads=(2, 4, 4),
        downsamples=(True, False, False), outlook_attention=(True, False, False),
        post_layers=('ca',), stem_hidden_dim=16)
    return _create_volo('test_volo', pretrained=pretrained, **dict(model_args, **kwargs))
