"""EfficientNet-family building blocks, NHWC
(reference: timm/models/_efficientnet_blocks.py:1-761).
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Attention2d, BatchNormAct2d, ConvNormAct, DropPath, LayerScale,
    MultiQueryAttention2d, SqueezeExcite, create_conv2d, get_aa_layer,
    get_act_fn, make_divisible, to_2tuple,
)

__all__ = [
    'ConvBnAct', 'DepthwiseSeparableConv', 'InvertedResidual', 'CondConvResidual',
    'UniversalInvertedResidual', 'MobileAttention', 'EdgeResidual', 'SqueezeExcite',
]


def num_groups(group_size, channels):
    if not group_size:
        return 1
    assert channels % group_size == 0
    return channels // group_size


class ConvBnAct(nnx.Module):
    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 0,
            pad_type: str = '',
            skip: bool = False,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        groups = num_groups(group_size, in_chs)
        self.has_skip = skip and stride == 1 and in_chs == out_chs
        aa_layer = get_aa_layer(aa_layer)
        use_aa = aa_layer is not None and stride > 1
        self.conv = create_conv2d(
            in_chs, out_chs, kernel_size, stride=1 if use_aa else stride,
            dilation=dilation, groups=groups,
            padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(out_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=out_chs, stride=stride, rngs=rngs) if use_aa else None
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv', num_chs=self.conv.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv(x))
        if self.aa is not None:
            x = self.aa(x)
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class DepthwiseSeparableConv(nnx.Module):
    """DW conv + PW conv (reference _efficientnet_blocks.py DepthwiseSeparableConv)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            dw_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            noskip: bool = False,
            pw_kernel_size: int = 1,
            pw_act: bool = False,
            s2d: int = 0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            se_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.has_skip = (stride == 1 and in_chs == out_chs) and not noskip
        self.has_pw_act = pw_act
        aa_layer = get_aa_layer(aa_layer)
        use_aa = aa_layer is not None and stride > 1

        # space-to-depth: 2x2/s2 conv front (reference _efficientnet_blocks.py:176-185)
        if s2d == 1:
            sd_chs = int(in_chs * 4)
            self.conv_s2d = create_conv2d(
                in_chs, sd_chs, 2, stride=2, padding='same',
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.bn_s2d = norm_layer(sd_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            dw_kernel_size = (dw_kernel_size + 1) // 2
            dw_pad_type = 'same' if dw_kernel_size == 2 else pad_type
            in_chs = sd_chs
            use_aa = False
        else:
            self.conv_s2d = None
            self.bn_s2d = None
            dw_pad_type = pad_type

        groups = num_groups(group_size, in_chs)
        self.conv_dw = create_conv2d(
            in_chs, in_chs, dw_kernel_size, stride=1 if use_aa else stride, dilation=dilation,
            groups=groups, padding=dw_pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(in_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=in_chs, stride=stride, rngs=rngs) if use_aa else None
        self.se = se_layer(in_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pw = create_conv2d(
            in_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(
            out_chs, apply_act=self.has_pw_act, act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pw', num_chs=self.conv_pw.out_features)

    def __call__(self, x):
        shortcut = x
        if self.conv_s2d is not None:
            x = self.bn_s2d(self.conv_s2d(x))
        x = self.bn1(self.conv_dw(x))
        if self.aa is not None:
            x = self.aa(x)
        if self.se is not None:
            x = self.se(x)
        x = self.bn2(self.conv_pw(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class InvertedResidual(nnx.Module):
    """MBConv (reference _efficientnet_blocks.py InvertedResidual)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            dw_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            noskip: bool = False,
            exp_ratio: float = 1.0,
            exp_kernel_size: int = 1,
            pw_kernel_size: int = 1,
            s2d: int = 0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            se_layer: Optional[Callable] = None,
            conv_kwargs: Optional[dict] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        conv_kwargs = conv_kwargs or {}
        self.has_skip = (in_chs == out_chs and stride == 1) and not noskip
        aa_layer = get_aa_layer(aa_layer)
        use_aa = aa_layer is not None and stride > 1

        # space-to-depth front (reference _efficientnet_blocks.py:276-287)
        if s2d == 1:
            sd_chs = int(in_chs * 4)
            self.conv_s2d = create_conv2d(
                in_chs, sd_chs, 2, stride=2, padding='same',
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.bn_s2d = norm_layer(sd_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            dw_kernel_size = (dw_kernel_size + 1) // 2
            dw_pad_type = 'same' if dw_kernel_size == 2 else pad_type
            in_chs = sd_chs
            use_aa = False
        else:
            self.conv_s2d = None
            self.bn_s2d = None
            dw_pad_type = pad_type

        mid_chs = make_divisible(in_chs * exp_ratio)
        groups = num_groups(group_size, mid_chs)

        self.conv_pw = create_conv2d(
            in_chs, mid_chs, exp_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs, **conv_kwargs)
        self.bn1 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv_dw = create_conv2d(
            mid_chs, mid_chs, dw_kernel_size, stride=1 if use_aa else stride, dilation=dilation,
            groups=groups, padding=dw_pad_type or None, dtype=dtype, param_dtype=param_dtype,
            rngs=rngs, **conv_kwargs)
        self.bn2 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=mid_chs, stride=stride, rngs=rngs) if use_aa else None
        self.se = se_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pwl = create_conv2d(
            mid_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs, **conv_kwargs)
        self.bn3 = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pwl', num_chs=self.conv_pwl.out_features)

    def __call__(self, x):
        shortcut = x
        if self.conv_s2d is not None:
            x = self.bn_s2d(self.conv_s2d(x))
        x = self.bn1(self.conv_pw(x))
        x = self.bn2(self.conv_dw(x))
        if self.aa is not None:
            x = self.aa(x)
        if self.se is not None:
            x = self.se(x)
        x = self.bn3(self.conv_pwl(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class CondConvResidual(InvertedResidual):
    """Inverted residual with CondConv expert routing
    (reference _efficientnet_blocks.py:612-677): a sigmoid routing head over
    globally-pooled input mixes per-example expert kernels for all three convs."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            num_experts: int = 0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
            **kwargs,
    ):
        self.num_experts = num_experts
        super().__init__(
            in_chs, out_chs, conv_kwargs=dict(num_experts=num_experts),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs, **kwargs)
        from ..layers import trunc_normal_, zeros_
        self.routing_fn = nnx.Linear(
            in_chs, num_experts, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        shortcut = x
        pooled = x.mean(axis=(1, 2))  # CondConv routing over NHWC spatial dims
        routing_weights = jax.nn.sigmoid(self.routing_fn(pooled))
        x = self.bn1(self.conv_pw(x, routing_weights))
        x = self.bn2(self.conv_dw(x, routing_weights))
        if self.se is not None:
            x = self.se(x)
        x = self.bn3(self.conv_pwl(x, routing_weights))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class UniversalInvertedResidual(nnx.Module):
    """Universal Inverted Bottleneck (MobileNetV4)
    (reference _efficientnet_blocks.py:342-489): optional dw at start/mid/end
    around the pw expand/project, with layer scale."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            dw_kernel_size_start: int = 0,
            dw_kernel_size_mid: int = 3,
            dw_kernel_size_end: int = 0,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            noskip: bool = False,
            exp_ratio: float = 1.0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            se_layer: Optional[Callable] = None,
            conv_kwargs: Optional[dict] = None,
            drop_path_rate: float = 0.0,
            layer_scale_init_value: Optional[float] = 1e-5,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        conv_kwargs = conv_kwargs or {}
        self.has_skip = (in_chs == out_chs and stride == 1) and not noskip
        if stride > 1:
            assert dw_kernel_size_start or dw_kernel_size_mid or dw_kernel_size_end
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if dw_kernel_size_start:
            dw_start_stride = stride if not dw_kernel_size_mid else 1
            self.dw_start = ConvNormAct(
                in_chs, in_chs, dw_kernel_size_start, stride=dw_start_stride, dilation=dilation,
                groups=num_groups(group_size, in_chs), padding=pad_type or None, apply_act=False,
                act_layer=act_layer, norm_layer=norm_layer, aa_layer=aa_layer, **conv_kwargs, **kw)
        else:
            self.dw_start = None

        mid_chs = make_divisible(in_chs * exp_ratio)
        self.pw_exp = ConvNormAct(
            in_chs, mid_chs, 1, padding=pad_type or None,
            act_layer=act_layer, norm_layer=norm_layer, **conv_kwargs, **kw)

        if dw_kernel_size_mid:
            self.dw_mid = ConvNormAct(
                mid_chs, mid_chs, dw_kernel_size_mid, stride=stride, dilation=dilation,
                groups=num_groups(group_size, mid_chs), padding=pad_type or None,
                act_layer=act_layer, norm_layer=norm_layer, aa_layer=aa_layer, **conv_kwargs, **kw)
        else:
            self.dw_mid = None

        self.se = se_layer(mid_chs, act_layer=act_layer, **kw) if se_layer else None

        self.pw_proj = ConvNormAct(
            mid_chs, out_chs, 1, padding=pad_type or None, apply_act=False,
            act_layer=act_layer, norm_layer=norm_layer, **conv_kwargs, **kw)

        if dw_kernel_size_end:
            dw_end_stride = stride if not dw_kernel_size_start and not dw_kernel_size_mid else 1
            if dw_end_stride > 1:
                assert not aa_layer
            self.dw_end = ConvNormAct(
                out_chs, out_chs, dw_kernel_size_end, stride=dw_end_stride, dilation=dilation,
                groups=num_groups(group_size, out_chs), padding=pad_type or None, apply_act=False,
                act_layer=act_layer, norm_layer=norm_layer, **conv_kwargs, **kw)
        else:
            self.dw_end = None

        self.layer_scale = LayerScale(out_chs, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs) \
            if layer_scale_init_value is not None else None
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='pw_proj.conv', num_chs=self.pw_proj.conv.out_features)

    def __call__(self, x):
        shortcut = x
        if self.dw_start is not None:
            x = self.dw_start(x)
        x = self.pw_exp(x)
        if self.dw_mid is not None:
            x = self.dw_mid(x)
        if self.se is not None:
            x = self.se(x)
        x = self.pw_proj(x)
        if self.dw_end is not None:
            x = self.dw_end(x)
        if self.layer_scale is not None:
            x = self.layer_scale(x)
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class MobileAttention(nnx.Module):
    """Mobile attention block (MobileNetV4 hybrid)
    (reference _efficientnet_blocks.py:489-610): norm → (multi-query or plain)
    2D attention → layer scale, with optional per-block CPE dw conv."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            stride: int = 1,
            dw_kernel_size: int = 3,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            num_heads: int = 8,
            key_dim: int = 64,
            value_dim: int = 64,
            use_multi_query: bool = False,
            query_strides=(1, 1),
            kv_stride: int = 1,
            cpe_dw_kernel_size: int = 3,
            noskip: bool = False,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            layer_scale_init_value: Optional[float] = 1e-5,
            use_bias: bool = False,
            use_cpe: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.has_skip = (stride == 1 and in_chs == out_chs) and not noskip
        self.query_strides = to_2tuple(query_strides)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if use_cpe:
            self.conv_cpe_dw = create_conv2d(
                in_chs, in_chs, cpe_dw_kernel_size, dilation=dilation, depthwise=True, bias=True, **kw)
        else:
            self.conv_cpe_dw = None

        self.norm = norm_layer(in_chs, apply_act=False, **kw)

        if num_heads is None:
            assert in_chs % key_dim == 0
            num_heads = in_chs // key_dim

        # raw norm class for the attention-internal norms (no act composite)
        from ..layers import BatchNorm2d
        if use_multi_query:
            self.attn = MultiQueryAttention2d(
                in_chs,
                dim_out=out_chs,
                num_heads=num_heads,
                key_dim=key_dim,
                value_dim=value_dim,
                query_strides=query_strides,
                kv_stride=kv_stride,
                dw_kernel_size=dw_kernel_size,
                dilation=dilation,
                padding=pad_type,
                attn_drop=attn_drop,
                proj_drop=proj_drop,
                norm_layer=BatchNorm2d,
                **kw,
            )
        else:
            self.attn = Attention2d(
                in_chs, dim_out=out_chs, num_heads=num_heads,
                attn_drop=attn_drop, proj_drop=proj_drop, bias=use_bias, **kw)

        self.layer_scale = LayerScale(out_chs, layer_scale_init_value, param_dtype=param_dtype, rngs=rngs) \
            if layer_scale_init_value is not None else None
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='attn', num_chs=self.attn.proj.out_features
                    if hasattr(self.attn, 'proj') else None)

    def __call__(self, x):
        if self.conv_cpe_dw is not None:
            x = x + self.conv_cpe_dw(x)
        shortcut = x
        x = self.norm(x)
        x = self.attn(x)
        if self.layer_scale is not None:
            x = self.layer_scale(x)
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class EdgeResidual(nnx.Module):
    """FusedMBConv (reference _efficientnet_blocks.py EdgeResidual)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            exp_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 0,
            pad_type: str = '',
            force_in_chs: int = 0,
            noskip: bool = False,
            exp_ratio: float = 1.0,
            pw_kernel_size: int = 1,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            se_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if force_in_chs > 0:
            mid_chs = make_divisible(force_in_chs * exp_ratio)
        else:
            mid_chs = make_divisible(in_chs * exp_ratio)
        groups = num_groups(group_size, mid_chs)
        self.has_skip = (in_chs == out_chs and stride == 1) and not noskip
        aa_layer = get_aa_layer(aa_layer)
        use_aa = aa_layer is not None and stride > 1

        self.conv_exp = create_conv2d(
            in_chs, mid_chs, exp_kernel_size, stride=1 if use_aa else stride, dilation=dilation,
            groups=groups, padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.aa = aa_layer(channels=mid_chs, stride=stride, rngs=rngs) if use_aa else None
        self.se = se_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pwl = create_conv2d(
            mid_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pwl', num_chs=self.conv_pwl.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv_exp(x))
        if self.aa is not None:
            x = self.aa(x)
        if self.se is not None:
            x = self.se(x)
        x = self.bn2(self.conv_pwl(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x
