"""EfficientNet-family building blocks, NHWC
(reference: timm/models/_efficientnet_blocks.py:1-761).
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, DropPath, SqueezeExcite, create_conv2d, get_act_fn, make_divisible

__all__ = ['ConvBnAct', 'DepthwiseSeparableConv', 'InvertedResidual', 'EdgeResidual', 'SqueezeExcite']


def num_groups(group_size, channels):
    if not group_size:
        return 1
    assert channels % group_size == 0
    return channels // group_size


class ConvBnAct(nnx.Module):
    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 0,
            pad_type: str = '',
            skip: bool = False,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        groups = num_groups(group_size, in_chs)
        self.has_skip = skip and stride == 1 and in_chs == out_chs
        self.conv = create_conv2d(
            in_chs, out_chs, kernel_size, stride=stride, dilation=dilation, groups=groups,
            padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(out_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv', num_chs=self.conv.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class DepthwiseSeparableConv(nnx.Module):
    """DW conv + PW conv (reference _efficientnet_blocks.py DepthwiseSeparableConv)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            dw_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            noskip: bool = False,
            pw_kernel_size: int = 1,
            pw_act: bool = False,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            se_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.has_skip = (stride == 1 and in_chs == out_chs) and not noskip
        self.has_pw_act = pw_act

        self.conv_dw = create_conv2d(
            in_chs, in_chs, dw_kernel_size, stride=stride, dilation=dilation,
            depthwise=True, padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(in_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = se_layer(in_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pw = create_conv2d(
            in_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(
            out_chs, apply_act=self.has_pw_act, act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pw', num_chs=self.conv_pw.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv_dw(x))
        if self.se is not None:
            x = self.se(x)
        x = self.bn2(self.conv_pw(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class InvertedResidual(nnx.Module):
    """MBConv (reference _efficientnet_blocks.py InvertedResidual)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            dw_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 1,
            pad_type: str = '',
            noskip: bool = False,
            exp_ratio: float = 1.0,
            exp_kernel_size: int = 1,
            pw_kernel_size: int = 1,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            se_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        mid_chs = make_divisible(in_chs * exp_ratio)
        self.has_skip = (in_chs == out_chs and stride == 1) and not noskip

        self.conv_pw = create_conv2d(
            in_chs, mid_chs, exp_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv_dw = create_conv2d(
            mid_chs, mid_chs, dw_kernel_size, stride=stride, dilation=dilation,
            depthwise=True, padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = se_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pwl = create_conv2d(
            mid_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn3 = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pwl', num_chs=self.conv_pwl.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv_pw(x))
        x = self.bn2(self.conv_dw(x))
        if self.se is not None:
            x = self.se(x)
        x = self.bn3(self.conv_pwl(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x


class EdgeResidual(nnx.Module):
    """FusedMBConv (reference _efficientnet_blocks.py EdgeResidual)."""

    def __init__(
            self,
            in_chs: int,
            out_chs: int,
            exp_kernel_size: int = 3,
            stride: int = 1,
            dilation: int = 1,
            group_size: int = 0,
            pad_type: str = '',
            force_in_chs: int = 0,
            noskip: bool = False,
            exp_ratio: float = 1.0,
            pw_kernel_size: int = 1,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            se_layer: Optional[Callable] = None,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if force_in_chs > 0:
            mid_chs = make_divisible(force_in_chs * exp_ratio)
        else:
            mid_chs = make_divisible(in_chs * exp_ratio)
        self.has_skip = (in_chs == out_chs and stride == 1) and not noskip

        self.conv_exp = create_conv2d(
            in_chs, mid_chs, exp_kernel_size, stride=stride, dilation=dilation,
            padding=pad_type or None, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.se = se_layer(mid_chs, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if se_layer else None
        self.conv_pwl = create_conv2d(
            mid_chs, out_chs, pw_kernel_size, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(out_chs, apply_act=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path = DropPath(drop_path_rate, rngs=rngs)

    def feature_info(self, location):
        return dict(module='conv_pwl', num_chs=self.conv_pwl.out_features)

    def __call__(self, x):
        shortcut = x
        x = self.bn1(self.conv_exp(x))
        if self.se is not None:
            x = self.se(x)
        x = self.bn2(self.conv_pwl(x))
        if self.has_skip:
            x = self.drop_path(x) + shortcut
        return x
