"""Torch-checkpoint → timm_tpu state-dict conversion.

Lets this framework load the reference's released weights for parity testing
(reference weight layouts: timm/models/*.py checkpoint_filter_fn families).

Conversion rules (torch → flax/nnx):
  Linear  .weight (O, I)       → .kernel (I, O)        [transpose]
  Conv2d  .weight (O, I, H, W) → .kernel (H, W, I, O)  [permute 2,3,1,0]
  Norm    .weight              → .scale
  BatchNorm .running_mean/var  → .mean / .var
Names otherwise match because module trees mirror the reference contract.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = ['load_torch_state_dict', 'convert_torch_state_dict']


def load_torch_state_dict(path: str, use_ema: bool = True) -> Dict[str, np.ndarray]:
    import torch
    ckpt = torch.load(path, map_location='cpu', weights_only=True)
    if isinstance(ckpt, dict):
        for key in (('state_dict_ema', 'model_ema') if use_ema else ()) + ('state_dict', 'model'):
            if key in ckpt and isinstance(ckpt[key], dict):
                ckpt = ckpt[key]
                break
    return {k: v.numpy() if hasattr(v, 'numpy') else np.asarray(v) for k, v in ckpt.items()}


def convert_torch_state_dict(state_dict: Dict[str, np.ndarray], model=None) -> Dict[str, np.ndarray]:
    """Mechanical torch→nnx layout conversion keyed on target shapes."""
    from ._helpers import model_state_dict
    target = model_state_dict(model) if model is not None else None
    out = {}
    for k, v in state_dict.items():
        v = np.asarray(v)
        nk, nv = k, v
        if k.endswith('.running_mean'):
            nk = k[:-len('.running_mean')] + '.mean'
        elif k.endswith('.running_var'):
            nk = k[:-len('.running_var')] + '.var'
        elif k.endswith('num_batches_tracked'):
            continue
        elif k.endswith('.weight'):
            base = k[:-len('.weight')]
            if v.ndim == 4:  # conv OIHW → HWIO
                nk, nv = base + '.kernel', v.transpose(2, 3, 1, 0)
            elif v.ndim == 2:  # linear (O,I) → (I,O)
                nk, nv = base + '.kernel', v.T
            elif v.ndim == 1:
                if target is not None and base + '.weight' in target:
                    nk = base + '.weight'  # e.g. GRN keeps torch naming
                else:
                    nk = base + '.scale'  # norm affine
                    if target is not None and nk not in target and base + '.kernel' in target:
                        nk = base + '.kernel'
            else:
                nk = base + '.kernel'
        # verify/auto-correct against target shapes when available
        if target is not None and nk in target and tuple(target[nk].shape) != tuple(nv.shape):
            if target[nk].size == nv.size:
                nv = nv.reshape(target[nk].shape)
        out[nk] = nv
    return out
