"""Model registry (reference: timm/models/_registry.py:1-352).

Same public contract: `@register_model` on entrypoint functions, `arch.tag`
pretrained tags, fnmatch-based `list_models`, per-module export tracking.
"""
from __future__ import annotations

import fnmatch
import re
import sys
from collections import defaultdict, deque
from copy import deepcopy
from dataclasses import replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple, Union

from ._pretrained import DefaultCfg, PretrainedCfg

__all__ = [
    'register_model', 'generate_default_cfgs', 'list_models', 'list_pretrained',
    'is_model', 'model_entrypoint', 'list_modules', 'is_model_in_modules',
    'get_pretrained_cfg', 'get_pretrained_cfg_value', 'is_model_pretrained',
    'split_model_name_tag', 'get_arch_name', 'get_arch_pretrained_cfgs',
]

_module_to_models: Dict[str, Set[str]] = defaultdict(set)
_model_to_module: Dict[str, str] = {}
_model_entrypoints: Dict[str, Callable[..., Any]] = {}
_model_has_pretrained: Set[str] = set()
_model_default_cfgs: Dict[str, DefaultCfg] = {}
_model_pretrained_cfgs: Dict[str, PretrainedCfg] = {}
_model_with_tags: Dict[str, List[str]] = defaultdict(list)
_deprecated_models: Dict[str, Optional[str]] = {}


def split_model_name_tag(model_name: str, no_tag: str = '') -> Tuple[str, str]:
    model_name, *tag_list = model_name.split('.', 1)
    tag = tag_list[0] if tag_list else no_tag
    return model_name, tag


def get_arch_name(model_name: str) -> str:
    return split_model_name_tag(model_name)[0]


def generate_default_cfgs(cfgs: Dict[str, Union[Dict[str, Any], PretrainedCfg]]) -> Dict[str, DefaultCfg]:
    out = defaultdict(DefaultCfg)
    default_set = set()  # archs with a default (first or explicitly-starred) tag

    for k, v in cfgs.items():
        if isinstance(v, dict):
            v = PretrainedCfg(**v)
        has_weights = v.has_weights
        model, tag = split_model_name_tag(k)
        is_default_set = model in default_set
        priority = (has_weights and not tag) or (tag.endswith('*') and not is_default_set)
        tag = tag.strip('*')
        default_cfg = out[model]
        if priority:
            default_cfg.tags.insert(0, tag)
            default_set.add(model)
        elif has_weights and not default_cfg.is_pretrained:
            default_cfg.tags.insert(0, tag)
        else:
            default_cfg.tags.append(tag)
        if has_weights:
            default_cfg.is_pretrained = True
        default_cfg.cfgs[tag] = v

    return dict(out)


def register_model(fn: Callable) -> Callable:
    mod = sys.modules[fn.__module__]
    module_name = fn.__module__.split('.')[-1]
    model_name = fn.__name__

    if hasattr(mod, '__all__'):
        mod.__all__.append(model_name)
    else:
        mod.__all__ = [model_name]

    _model_entrypoints[model_name] = fn
    _model_to_module[model_name] = module_name
    _module_to_models[module_name].add(model_name)

    default_cfg = getattr(mod, 'default_cfgs', {}).get(model_name, None)
    if default_cfg is not None:
        if not isinstance(default_cfg, DefaultCfg):
            assert isinstance(default_cfg, dict)
            default_cfg = DefaultCfg(tags=[''], cfgs={'': PretrainedCfg(**default_cfg)})
        for tag_idx, tag in enumerate(default_cfg.tags):
            is_default = tag_idx == 0
            pretrained_cfg = default_cfg.cfgs[tag]
            model_name_tag = '.'.join([model_name, tag]) if tag else model_name
            pretrained_cfg = replace(pretrained_cfg, architecture=model_name, tag=tag if tag else None)
            if is_default:
                _model_pretrained_cfgs[model_name] = pretrained_cfg
                if pretrained_cfg.has_weights:
                    _model_has_pretrained.add(model_name)
            if tag:
                _model_pretrained_cfgs[model_name_tag] = pretrained_cfg
                if pretrained_cfg.has_weights:
                    _model_has_pretrained.add(model_name_tag)
                _model_with_tags[model_name].append(model_name_tag)
            else:
                _model_with_tags[model_name].append(model_name)
        _model_default_cfgs[model_name] = default_cfg
    return fn


def _natural_key(string_: str) -> List[Union[int, str]]:
    return [int(s) if s.isdigit() else s for s in re.split(r'(\d+)', string_.lower())]


def _expand_filter(filter_: str) -> List[str]:
    filter_base, filter_tag = split_model_name_tag(filter_)
    if not filter_tag:
        return ['.'.join([filter_base, '*']), filter_]
    return [filter_]


def list_models(
        filter: Union[str, List[str]] = '',
        module: Union[str, List[str]] = '',
        pretrained: bool = False,
        exclude_filters: Union[str, List[str]] = '',
        name_matches_cfg: bool = False,
        include_tags: Optional[bool] = None,
) -> List[str]:
    if filter:
        include_filters = filter if isinstance(filter, (tuple, list)) else [filter]
    else:
        include_filters = []
    include_tags = pretrained if include_tags is None else include_tags

    if not module:
        all_models: Iterable[str] = _model_entrypoints.keys()
    else:
        models: Set[str] = set()
        if isinstance(module, str):
            module = [module]
        for m in module:
            models.update(_module_to_models[m])
        all_models = models
    all_models = [m for m in all_models if m not in _deprecated_models]

    if include_tags:
        models_with_tags: Set[str] = set()
        for m in all_models:
            models_with_tags.update(_model_with_tags[m])
        all_models = list(models_with_tags)
        include_filters = [ef for f in include_filters for ef in _expand_filter(f)]
        exclude_filters = [ef for f in ([exclude_filters] if isinstance(exclude_filters, str) else exclude_filters) for ef in _expand_filter(f)] if exclude_filters else exclude_filters

    if include_filters:
        models = set()
        for f in include_filters:
            include_models = fnmatch.filter(all_models, f)
            if include_models:
                models.update(include_models)
    else:
        models = set(all_models)

    if exclude_filters:
        if not isinstance(exclude_filters, (tuple, list)):
            exclude_filters = [exclude_filters]
        for xf in exclude_filters:
            exclude_models = fnmatch.filter(models, xf)
            if exclude_models:
                models = models.difference(exclude_models)

    if pretrained:
        models = _model_has_pretrained.intersection(models)

    if name_matches_cfg:
        models = set(_model_pretrained_cfgs).intersection(models)

    return sorted(models, key=_natural_key)


def list_pretrained(filter: Union[str, List[str]] = '', exclude_filters: str = '') -> List[str]:
    return list_models(filter=filter, pretrained=True, exclude_filters=exclude_filters, include_tags=True)


def is_model(model_name: str) -> bool:
    arch_name = get_arch_name(model_name)
    return arch_name in _model_entrypoints


def model_entrypoint(model_name: str, module_filter: Optional[str] = None) -> Callable[..., Any]:
    arch_name = get_arch_name(model_name)
    if module_filter and arch_name not in _module_to_models.get(module_filter, {}):
        raise RuntimeError(f'Model ({model_name}) not found in module {module_filter}.')
    if arch_name not in _model_entrypoints:
        raise RuntimeError(f'Unknown model ({model_name})')
    return _model_entrypoints[arch_name]


def list_modules() -> List[str]:
    return sorted(_module_to_models.keys())


def is_model_in_modules(model_name: str, module_names: Sequence[str]) -> bool:
    arch_name = get_arch_name(model_name)
    return any(arch_name in _module_to_models[n] for n in module_names)


def is_model_pretrained(model_name: str) -> bool:
    return model_name in _model_has_pretrained


def get_pretrained_cfg(model_name: str, allow_unregistered: bool = True) -> Optional[PretrainedCfg]:
    if model_name in _model_pretrained_cfgs:
        return deepcopy(_model_pretrained_cfgs[model_name])
    arch_name, tag = split_model_name_tag(model_name)
    if arch_name in _model_default_cfgs:
        raise RuntimeError(f'Invalid pretrained tag ({tag}) for {arch_name}.')
    if allow_unregistered:
        return None
    raise RuntimeError(f'Model architecture ({arch_name}) has no pretrained cfg registered.')


def get_pretrained_cfg_value(model_name: str, cfg_key: str) -> Optional[Any]:
    cfg = get_pretrained_cfg(model_name, allow_unregistered=False)
    return getattr(cfg, cfg_key, None)


def get_arch_pretrained_cfgs(model_name: str) -> Dict[str, PretrainedCfg]:
    arch_name, _ = split_model_name_tag(model_name)
    model_names = _model_with_tags.get(arch_name, [])
    return {m: _model_pretrained_cfgs[m] for m in model_names if m in _model_pretrained_cfgs}
