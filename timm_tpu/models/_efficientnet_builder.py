"""EfficientNet arch-string decoder + stage builder
(reference: timm/models/_efficientnet_builder.py:43-581).

The same block-string DSL as the reference: e.g. 'ir_r4_k3_s2_e6_c128_se0.25'
decodes to 4 repeats of an InvertedResidual k3 s2 expand-6 out-128 w/ SE 0.25.
"""
from __future__ import annotations

import logging
import math
import re
from copy import deepcopy
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, SqueezeExcite, get_aa_layer, get_act_fn, make_divisible
from ._efficientnet_blocks import (
    CondConvResidual, ConvBnAct, DepthwiseSeparableConv, EdgeResidual,
    InvertedResidual, MobileAttention, UniversalInvertedResidual,
)

_logger = logging.getLogger(__name__)

__all__ = ['EfficientNetBuilder', 'decode_arch_def', 'round_channels', 'resolve_bn_args', 'resolve_act_layer']

BN_MOMENTUM_TF_DEFAULT = 1 - 0.99
BN_EPS_TF_DEFAULT = 1e-3


def resolve_bn_args(kwargs):
    bn_args = {}
    if kwargs.pop('bn_tf', False):
        bn_args = dict(momentum=BN_MOMENTUM_TF_DEFAULT, eps=BN_EPS_TF_DEFAULT)
    bn_momentum = kwargs.pop('bn_momentum', None)
    if bn_momentum is not None:
        bn_args['momentum'] = bn_momentum
    bn_eps = kwargs.pop('bn_eps', None)
    if bn_eps is not None:
        bn_args['eps'] = bn_eps
    return bn_args


def resolve_act_layer(kwargs, default='relu'):
    return kwargs.pop('act_layer', default) or default


def round_channels(channels, multiplier: float = 1.0, divisor: int = 8, channel_min=None, round_limit: float = 0.9):
    """(reference _efficientnet_builder.py:62)."""
    if not multiplier:
        return channels
    return make_divisible(channels * multiplier, divisor, channel_min, round_limit=round_limit)


def _parse_ksize(ss: str):
    if ss.isdigit():
        return int(ss)
    return [int(k) for k in ss.split('.')]  # mixed kernels (MixNet) stay a list


def _decode_block_str(block_str: str) -> Dict[str, Any]:
    """Decode one block definition string (reference _efficientnet_builder.py:81)."""
    assert isinstance(block_str, str)
    ops = block_str.split('_')
    block_type = ops[0]
    ops = ops[1:]
    options: Dict[str, str] = {}
    skip = None
    for op in ops:
        if op == 'noskip':
            skip = False
        elif op == 'skip':
            skip = True
        elif op.startswith('n'):
            # activation fn
            options['n'] = op[1:]
        else:
            splits = re.split(r'(\d.*)', op)
            if len(splits) >= 2:
                key, value = splits[:2]
                options[key] = value

    # act-fn abbreviations used in block strings (reference _decode_block_str)
    _ACT_ABBREV = {'re': 'relu', 'r6': 'relu6', 'hs': 'hard_swish', 'sw': 'swish',
                   'mi': 'mish', 'ge': 'gelu', 'si': 'silu'}
    act_layer = options.get('n', None)
    if act_layer is not None:
        act_layer = _ACT_ABBREV.get(act_layer, act_layer)
    start_kwargs = dict(
        block_type=block_type,
        out_chs=int(options['c']),
        stride=int(options.get('s', 1)),
        act_layer=act_layer,
    )
    num_repeat = int(options.get('r', 1))

    if block_type == 'ir':
        start_kwargs.update(dict(
            dw_kernel_size=_parse_ksize(options['k']),
            exp_kernel_size=_parse_ksize(options.get('a', '1')),
            pw_kernel_size=_parse_ksize(options.get('p', '1')),
            exp_ratio=float(options.get('e', 1.0)),
            se_ratio=float(options.get('se', 0.0)),
            noskip=skip is False,
            s2d=int(options.get('d', 0)) > 0,
        ))
        if 'cc' in options:
            start_kwargs['num_experts'] = int(options['cc'])
    elif block_type == 'ds' or block_type == 'dsa':
        start_kwargs.update(dict(
            dw_kernel_size=_parse_ksize(options['k']),
            pw_kernel_size=_parse_ksize(options.get('p', '1')),
            se_ratio=float(options.get('se', 0.0)),
            pw_act=block_type == 'dsa',
            noskip=block_type == 'dsa' or skip is False,
            s2d=int(options.get('d', 0)) > 0,
        ))
    elif block_type == 'er':
        start_kwargs.update(dict(
            exp_kernel_size=_parse_ksize(options['k']),
            pw_kernel_size=_parse_ksize(options.get('p', '1')),
            exp_ratio=float(options.get('e', 1.0)),
            se_ratio=float(options.get('se', 0.0)),
            force_in_chs=int(options.get('fc', 0)),
            noskip=skip is False,
        ))
    elif block_type == 'cn':
        start_kwargs.update(dict(
            kernel_size=int(options['k']),
            skip=skip is True,
        ))
    elif block_type == 'uir':
        # dw kernel sizes at start/mid/end; 0 disables ('a'/'p' overloaded)
        start_kwargs.update(dict(
            dw_kernel_size_start=_parse_ksize(options.get('a', '0')),
            dw_kernel_size_mid=_parse_ksize(options['k']),
            dw_kernel_size_end=_parse_ksize(options.get('p', '0')),
            exp_ratio=float(options.get('e', 1.0)),
            se_ratio=float(options.get('se', 0.0)),
            noskip=skip is False,
        ))
    elif block_type in ('mha', 'mqa'):
        kv_dim = int(options['d'])
        start_kwargs.update(dict(
            dw_kernel_size=_parse_ksize(options['k']),
            num_heads=int(options['h']),
            key_dim=kv_dim,
            value_dim=kv_dim,
            kv_stride=int(options.get('v', 1)),
            noskip=skip is False,
        ))
    else:
        raise AssertionError(f'Unknown block type ({block_type})')

    if 'gs' in options:
        start_kwargs['group_size'] = int(options['gs'])

    return start_kwargs, num_repeat


def _scale_stage_depth(stack_args, repeats, depth_multiplier=1.0, depth_trunc='ceil'):
    """(reference _efficientnet_builder.py:~230)."""
    num_repeat = sum(repeats)
    if depth_trunc == 'round':
        num_repeat_scaled = max(1, round(num_repeat * depth_multiplier))
    else:
        num_repeat_scaled = int(math.ceil(num_repeat * depth_multiplier))

    repeats_scaled = []
    for r in repeats[::-1]:
        rs = max(1, round((r / num_repeat * num_repeat_scaled)))
        repeats_scaled.append(rs)
        num_repeat -= r
        num_repeat_scaled -= rs
    repeats_scaled = repeats_scaled[::-1]

    sa_scaled = []
    for ba, rep in zip(stack_args, repeats_scaled):
        sa_scaled.extend([deepcopy(ba) for _ in range(rep)])
    return sa_scaled


def decode_arch_def(
        arch_def: List[List[str]],
        depth_multiplier: Union[float, tuple] = 1.0,
        depth_trunc: str = 'ceil',
        experts_multiplier: int = 1,
        fix_first_last: bool = False,
        group_size=None,
):
    """(reference _efficientnet_builder.py:270)."""
    arch_args = []
    if isinstance(depth_multiplier, tuple):
        assert len(depth_multiplier) == len(arch_def)
    else:
        depth_multiplier = (depth_multiplier,) * len(arch_def)
    for stack_idx, (block_strings, multiplier) in enumerate(zip(arch_def, depth_multiplier)):
        assert isinstance(block_strings, list)
        stack_args = []
        repeats = []
        for block_str in block_strings:
            ba, rep = _decode_block_str(block_str)
            if ba.get('num_experts', 0) > 0 and experts_multiplier > 1:
                ba['num_experts'] *= experts_multiplier
            if group_size is not None:
                ba.setdefault('group_size', group_size)
            stack_args.append(ba)
            repeats.append(rep)
        if fix_first_last and (stack_idx == 0 or stack_idx == len(arch_def) - 1):
            arch_args.append(_scale_stage_depth(stack_args, repeats, 1.0, depth_trunc))
        else:
            arch_args.append(_scale_stage_depth(stack_args, repeats, multiplier, depth_trunc))
    return arch_args


class EfficientNetBuilder:
    """Builds stage lists from decoded args (reference _efficientnet_builder.py:316)."""

    def __init__(
            self,
            output_stride: int = 32,
            pad_type: str = '',
            round_chs_fn: Callable = round_channels,
            se_from_exp: bool = False,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Callable] = None,
            se_layer: Callable = SqueezeExcite,
            drop_path_rate: float = 0.0,
            layer_scale_init_value: Optional[float] = None,
            feature_location: str = '',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.output_stride = output_stride
        self.pad_type = pad_type
        self.round_chs_fn = round_chs_fn
        self.se_from_exp = se_from_exp
        self.act_layer = act_layer
        self.norm_layer = norm_layer
        self.aa_layer = get_aa_layer(aa_layer)
        self.se_layer = se_layer
        import inspect
        _se_base = se_layer.func if isinstance(se_layer, partial) else se_layer
        try:
            _se_params = inspect.signature(_se_base.__init__).parameters
        except (TypeError, ValueError):
            _se_params = {}
        _se_bound = getattr(se_layer, 'keywords', {}) or {}
        self.se_has_ratio = 'rd_ratio' in _se_params or 'rd_ratio' in _se_bound
        self.se_plain_round = 'rd_round_fn' in _se_params and 'rd_round_fn' not in _se_bound
        self.drop_path_rate = drop_path_rate
        self.layer_scale_init_value = layer_scale_init_value
        self.dtype = dtype
        self.param_dtype = param_dtype
        self.rngs = rngs
        self.in_chs = None
        self.features = []

    def _make_block(self, ba: Dict, block_idx: int, block_count: int):
        drop_path_rate = self.drop_path_rate * block_idx / block_count
        bt = ba.pop('block_type')
        ba['in_chs'] = self.in_chs
        ba['out_chs'] = self.round_chs_fn(ba['out_chs'])
        s2d = ba.get('s2d', 0)
        if s2d > 0:
            # adjust while space2depth active (reference _efficientnet_builder.py:374-377)
            ba['out_chs'] *= 4
        if 'force_in_chs' in ba and ba['force_in_chs']:
            ba['force_in_chs'] = self.round_chs_fn(ba['force_in_chs'])
        ba['pad_type'] = self.pad_type
        ba['act_layer'] = ba.pop('act_layer', None) or self.act_layer
        ba['norm_layer'] = self.norm_layer
        if self.aa_layer is not None:
            ba['aa_layer'] = self.aa_layer
        se_ratio = ba.pop('se_ratio', 0.0)
        se_layer = None
        if se_ratio > 0.0 and self.se_layer is not None:
            if not self.se_from_exp:
                se_ratio /= ba.get('exp_ratio', 1.0)
            if s2d == 1:
                # adjust for start of space2depth
                se_ratio /= 4
            if self.se_plain_round:
                # EfficientNet-family SE uses plain rounding (reference
                # _efficientnet_blocks.py: rd_round_fn or round)
                se_layer = partial(self.se_layer, rd_ratio=se_ratio, rd_round_fn=round)
            elif self.se_has_ratio:
                se_layer = partial(self.se_layer, rd_ratio=se_ratio)
            else:
                # layer takes no ratio (reference builder drops it too)
                se_layer = self.se_layer
        common = dict(dtype=self.dtype, param_dtype=self.param_dtype, rngs=self.rngs)

        if bt == 'ir':
            ba.setdefault('s2d', 0)
            if ba.get('num_experts', 0):
                block = CondConvResidual(drop_path_rate=drop_path_rate, se_layer=se_layer, **ba, **common)
            else:
                block = InvertedResidual(drop_path_rate=drop_path_rate, se_layer=se_layer, **ba, **common)
        elif bt in ('ds', 'dsa'):
            ba.pop('exp_ratio', None)
            ba.pop('exp_kernel_size', None)
            block = DepthwiseSeparableConv(drop_path_rate=drop_path_rate, se_layer=se_layer, **ba, **common)
        elif bt == 'er':
            block = EdgeResidual(drop_path_rate=drop_path_rate, se_layer=se_layer, **ba, **common)
        elif bt == 'cn':
            block = ConvBnAct(drop_path_rate=drop_path_rate, **ba, **common)
        elif bt == 'uir':
            block = UniversalInvertedResidual(
                drop_path_rate=drop_path_rate, se_layer=se_layer,
                layer_scale_init_value=self.layer_scale_init_value, **ba, **common)
        elif bt in ('mqa', 'mha'):
            block = MobileAttention(
                drop_path_rate=drop_path_rate, use_multi_query=bt == 'mqa',
                layer_scale_init_value=self.layer_scale_init_value, **ba, **common)
        else:
            raise AssertionError(f'Unknown block type ({bt})')
        self.in_chs = ba['out_chs']
        return block

    def __call__(self, in_chs: int, model_block_args: List[List[Dict]]):
        self.in_chs = in_chs
        total_block_count = sum(len(s) for s in model_block_args)
        block_idx = 0
        current_stride = 2  # after stem
        current_dilation = 1
        stages = []
        self.features = []
        space2depth = 0
        for stack_idx, stack_args in enumerate(model_block_args):
            blocks = []
            for i, ba in enumerate(stack_args):
                ba = deepcopy(ba)
                if i > 0:
                    ba['stride'] = 1
                # space-to-depth region state machine
                # (reference _efficientnet_builder.py:471-484, 509-510)
                if not space2depth and ba.pop('s2d', False):
                    assert ba.get('stride', 1) == 1
                    space2depth = 1
                if space2depth > 0:
                    if space2depth == 2 and ba.get('stride', 1) == 2:
                        ba['stride'] = 1
                        # end s2d region: correct expansion relative to input
                        ba['exp_ratio'] /= 4
                        space2depth = 0
                    else:
                        ba['s2d'] = space2depth
                # stride→dilation conversion compounds across stages
                # (reference _efficientnet_builder.py:495-503)
                next_dilation = current_dilation
                if ba.get('stride', 1) > 1:
                    next_output_stride = current_stride * ba['stride']
                    if next_output_stride > self.output_stride:
                        next_dilation = current_dilation * ba['stride']
                        ba['stride'] = 1
                    else:
                        current_stride = next_output_stride
                ba['dilation'] = current_dilation
                current_dilation = next_dilation
                blocks.append(self._make_block(ba, block_idx, total_block_count))
                block_idx += 1
                if space2depth == 1:
                    space2depth = 2
            stages.append(nnx.List(blocks))
            self.features.append(dict(
                num_chs=self.in_chs, reduction=current_stride, module=f'blocks.{stack_idx}'))
        return stages
