"""`create_model` public entry (reference: timm/models/_factory.py:18-149)."""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Union
from urllib.parse import urlsplit

from ._helpers import load_checkpoint
from ._pretrained import PretrainedCfg
from ._registry import is_model, model_entrypoint, split_model_name_tag

__all__ = ['create_model', 'parse_model_name', 'safe_model_name']


def parse_model_name(model_name: str):
    if model_name.startswith('hf_hub'):
        model_name = model_name.replace('hf_hub', 'hf-hub')
    parsed = urlsplit(model_name)
    assert parsed.scheme in ('', 'timm', 'hf-hub', 'local-dir')
    if parsed.scheme == 'hf-hub':
        return parsed.scheme, os.path.join(parsed.netloc, parsed.path.lstrip('/')).rstrip('/')
    if parsed.scheme == 'local-dir':
        return parsed.scheme, os.path.join(parsed.netloc, parsed.path.lstrip('/')).rstrip('/')
    return 'timm', os.path.split(parsed.path)[-1]


def safe_model_name(model_name: str, remove_source: bool = True) -> str:
    def make_safe(name):
        return ''.join(c if c.isalnum() else '_' for c in name).rstrip('_')
    if remove_source:
        model_name = parse_model_name(model_name)[-1]
    return make_safe(model_name)


def create_model(
        model_name: str,
        pretrained: bool = False,
        pretrained_cfg: Optional[Union[str, Dict[str, Any], PretrainedCfg]] = None,
        pretrained_cfg_overlay: Optional[Dict[str, Any]] = None,
        checkpoint_path: str = '',
        cache_dir: Optional[str] = None,
        scriptable: Optional[bool] = None,
        exportable: Optional[bool] = None,
        no_jit: Optional[bool] = None,
        **kwargs,
):
    """Create a model by registry name, mirroring the reference contract.

    `hf-hub:`/`local-dir:` schemes resolve to a config + weights directory;
    in this environment only local dirs are reachable.
    """
    kwargs = {k: v for k, v in kwargs.items() if v is not None}

    model_source, model_name = parse_model_name(model_name)
    if model_source == 'hf-hub':
        raise RuntimeError(
            'hf-hub model sources require network egress; download the repo and use local-dir: instead.')
    if model_source == 'local-dir':
        import json
        cfg_path = os.path.join(model_name, 'config.json')
        with open(cfg_path) as f:
            cfg = json.load(f)
        arch = cfg.get('architecture')
        pretrained_cfg = cfg.get('pretrained_cfg', cfg)
        for fname in ('model.safetensors', 'model.npz'):
            fpath = os.path.join(model_name, fname)
            if os.path.exists(fpath):
                pretrained_cfg = dict(pretrained_cfg, file=fpath)
                break
        model_name = arch
    else:
        model_name, pretrained_tag = split_model_name_tag(model_name)
        if pretrained_tag and not pretrained_cfg:
            pretrained_cfg = pretrained_tag

    if not is_model(model_name):
        raise RuntimeError(f'Unknown model ({model_name})')

    create_fn = model_entrypoint(model_name)
    model = create_fn(
        pretrained=pretrained,
        pretrained_cfg=pretrained_cfg,
        pretrained_cfg_overlay=pretrained_cfg_overlay,
        **kwargs,
    )

    if checkpoint_path:
        load_checkpoint(model, checkpoint_path)
    return model
