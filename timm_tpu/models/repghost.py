"""RepGhostNet (reference: timm/models/repghost.py:1-584), TPU-native NHWC.

Ghost modules with a re-parameterizable fusion branch: at train time the cheap
dw conv output is summed with a parallel BN branch; `reparameterize()` folds
the BN branch into the dw conv (+bias) for deployment, matching the
reference's switch_to_deploy numerics.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, SelectAdaptivePool2d, SqueezeExcite, create_conv2d,
    make_divisible, trunc_normal_, zeros_,
)
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._efficientnet_blocks import ConvBnAct
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['RepGhostNet']

_SE_LAYER = partial(SqueezeExcite, gate_layer='hard_sigmoid', rd_round_fn=partial(make_divisible, divisor=4))


class RepGhostModule(nnx.Module):
    """(reference repghost.py:23-122): primary 1x1 conv-bn-relu, cheap dw
    conv-bn, plus a BN-only fusion branch summed in (reparam form folds it)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, dw_size=3, stride=1,
                 relu=True, reparam=True, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.out_chs = out_chs
        init_chs = out_chs
        new_chs = out_chs
        self.relu_out = relu
        # Sequential indices match the reference state dict (relu is paramless)
        self.primary_conv = nnx.List([
            create_conv2d(in_chs, init_chs, kernel_size, stride=stride, padding=kernel_size // 2, **kw),
            BatchNorm2d(init_chs, rngs=rngs),
        ])
        self.fusion_bn = nnx.List([BatchNorm2d(init_chs, rngs=rngs)]) if reparam else nnx.List([])
        self.cheap_operation = nnx.List([
            create_conv2d(init_chs, new_chs, dw_size, stride=1, padding=dw_size // 2, groups=init_chs, **kw),
            BatchNorm2d(new_chs, rngs=rngs),
        ])
        self.cheap_bias = None  # populated by reparameterize()

    def __call__(self, x):
        x1 = self.primary_conv[1](self.primary_conv[0](x))
        if self.relu_out:
            x1 = jax.nn.relu(x1)
        x2 = self.cheap_operation[0](x1)
        if len(self.cheap_operation) > 1:
            x2 = self.cheap_operation[1](x2)
        if self.cheap_bias is not None:
            x2 = x2 + self.cheap_bias[...].astype(x2.dtype)
        for bn in self.fusion_bn:
            x2 = x2 + bn(x1)
        if self.relu_out:
            x2 = jax.nn.relu(x2)
        return x2

    def reparameterize(self):
        """Fold cheap-op BN + fusion BN (an identity-conv + BN) into a single
        biased dw conv (reference repghost.py:66-122)."""
        if not len(self.fusion_bn):
            return
        conv = self.cheap_operation[0]
        bn = self.cheap_operation[1]
        kernel = conv.kernel[...]  # (kh, kw, 1, C) depthwise HWIO
        std = jnp.sqrt(bn.var[...] + bn.epsilon)
        t = (bn.scale[...] / std)
        k3 = kernel * t[None, None, None, :]
        b3 = bn.bias[...] - bn.mean[...] * bn.scale[...] / std
        kh = kernel.shape[0]
        for fbn in self.fusion_bn:
            stdf = jnp.sqrt(fbn.var[...] + fbn.epsilon)
            tf = fbn.scale[...] / stdf
            ident = jnp.zeros_like(k3).at[kh // 2, kh // 2, 0, :].set(tf)
            k3 = k3 + ident
            b3 = b3 + (fbn.bias[...] - fbn.mean[...] * fbn.scale[...] / stdf)
        conv.kernel[...] = k3
        self.cheap_operation = nnx.List([conv])
        self.cheap_bias = nnx.data(nnx.Param(b3))
        self.fusion_bn = nnx.List([])


class RepGhostBottleneck(nnx.Module):
    """(reference repghost.py:124-195)."""

    def __init__(self, in_chs, mid_chs, out_chs, dw_kernel_size=3, stride=1,
                 se_ratio=0.0, reparam=True, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        has_se = se_ratio is not None and se_ratio > 0.0
        self.stride = stride
        self.ghost1 = RepGhostModule(in_chs, mid_chs, relu=True, reparam=reparam, **kw)
        if stride > 1:
            self.conv_dw = create_conv2d(
                mid_chs, mid_chs, dw_kernel_size, stride=stride,
                padding=(dw_kernel_size - 1) // 2, groups=mid_chs, **kw)
            self.bn_dw = BatchNorm2d(mid_chs, rngs=rngs)
        else:
            self.conv_dw = None
            self.bn_dw = None
        self.se = _SE_LAYER(mid_chs, rd_ratio=se_ratio, **kw) if has_se else None
        self.ghost2 = RepGhostModule(mid_chs, out_chs, relu=False, reparam=reparam, **kw)
        if in_chs == out_chs and stride == 1:
            self.shortcut = None
        else:
            self.shortcut = nnx.List([
                create_conv2d(in_chs, in_chs, dw_kernel_size, stride=stride,
                              padding=(dw_kernel_size - 1) // 2, groups=in_chs, **kw),
                BatchNorm2d(in_chs, rngs=rngs),
                create_conv2d(in_chs, out_chs, 1, padding=0, **kw),
                BatchNorm2d(out_chs, rngs=rngs),
            ])

    def __call__(self, x):
        shortcut = x
        x = self.ghost1(x)
        if self.conv_dw is not None:
            x = self.bn_dw(self.conv_dw(x))
        if self.se is not None:
            x = self.se(x)
        x = self.ghost2(x)
        if self.shortcut is not None:
            for m in self.shortcut:
                shortcut = m(shortcut)
        return x + shortcut


class RepGhostNet(nnx.Module):
    """(reference repghost.py:197-372)."""

    def __init__(
            self,
            cfgs: List[List[List]],
            num_classes: int = 1000,
            width: float = 1.0,
            in_chans: int = 3,
            output_stride: int = 32,
            global_pool: str = 'avg',
            drop_rate: float = 0.2,
            reparam: bool = True,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.cfgs = cfgs
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        self.feature_info = []

        stem_chs = make_divisible(16 * width, 4)
        self.conv_stem = create_conv2d(in_chans, stem_chs, 3, stride=2, padding=1, **kw)
        self.feature_info.append(dict(num_chs=stem_chs, reduction=2, module='conv_stem'))
        self.bn1 = BatchNorm2d(stem_chs, rngs=rngs)

        prev_chs = stem_chs
        stages = []
        net_stride = 2
        stage_idx = 0
        exp_size = 16
        for cfg in cfgs:
            layers = []
            s = 1
            for k, exp_size, c, se_ratio, s in cfg:
                out_chs = make_divisible(c * width, 4)
                mid_chs = make_divisible(exp_size * width, 4)
                layers.append(RepGhostBottleneck(
                    prev_chs, mid_chs, out_chs, k, s, se_ratio=se_ratio, reparam=reparam, **kw))
                prev_chs = out_chs
            if s > 1:
                net_stride *= 2
                self.feature_info.append(dict(
                    num_chs=prev_chs, reduction=net_stride, module=f'blocks.{stage_idx}'))
            stages.append(nnx.List(layers))
            stage_idx += 1
        out_chs = make_divisible(exp_size * width * 2, 4)
        stages.append(nnx.List([ConvBnAct(prev_chs, out_chs, 1, **kw)]))
        self.pool_dim = prev_chs = out_chs
        self.blocks = nnx.List(stages)

        self.num_features = prev_chs
        self.head_hidden_size = out_chs = 1280
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        self.conv_head = create_conv2d(prev_chs, out_chs, 1, padding=0, bias=True, **kw)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.classifier = nnx.Linear(
            out_chs, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            **kw) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv_stem|bn1',
            blocks=[
                (r'^blocks\.(\d+)' if coarse else r'^blocks\.(\d+)\.(\d+)', None),
                (r'conv_head', (99999,)),
            ])

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=False)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Linear(
            self.head_hidden_size, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def convert_to_deploy(self):
        for stage in self.blocks:
            for blk in stage:
                if isinstance(blk, RepGhostBottleneck):
                    blk.ghost1.reparameterize()
                    blk.ghost2.reparameterize()

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = jax.nn.relu(self.bn1(self.conv_stem(x)))
        for stage in self.blocks:
            for blk in stage:
                x = blk(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        if x.ndim == 2:
            x = x[:, None, None, :]
        x = jax.nn.relu(self.conv_head(x))
        x = x.reshape(x.shape[0], -1)
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x
        return self.classifier(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        stage_ends = [-1] + [int(info['module'].split('.')[-1]) for info in self.feature_info[1:]]
        take_indices, max_index = feature_take_indices(len(stage_ends), indices)
        take_indices = [stage_ends[i] + 1 for i in take_indices]
        max_index = stage_ends[max_index]
        intermediates = []
        feat_idx = 0
        x = self.conv_stem(x)
        if feat_idx in take_indices:
            intermediates.append(x)
        x = jax.nn.relu(self.bn1(x))
        stages = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for feat_idx, stage in enumerate(stages, start=1):
            for blk in stage:
                x = blk(x)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        stage_ends = [-1] + [int(info['module'].split('.')[-1]) for info in self.feature_info[1:]]
        take_indices, max_index = feature_take_indices(len(stage_ends), indices)
        max_index = stage_ends[max_index]
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Sequential index remaps: primary_conv/cheap_operation/shortcut keep
    their indices; fusion_bn.0 maps 1:1; ghost relu entries are paramless."""
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        # reference SE convs are conv_reduce/conv_expand (ours fc1/fc2)
        k = k.replace('.se.conv_reduce.', '.se.fc1.').replace('.se.conv_expand.', '.se.fc2.')
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_repghostnet(variant, width=1.0, pretrained=False, **kwargs):
    """(reference repghost.py:389-427) — stage cfg table."""
    cfgs = [
        [[3, 8, 16, 0, 1]],
        [[3, 24, 24, 0, 2]],
        [[3, 36, 24, 0, 1]],
        [[5, 36, 40, 0.25, 2]],
        [[5, 60, 40, 0.25, 1]],
        [[3, 120, 80, 0, 2]],
        [[3, 100, 80, 0, 1],
         [3, 120, 80, 0, 1],
         [3, 120, 80, 0, 1],
         [3, 240, 112, 0.25, 1],
         [3, 336, 112, 0.25, 1]],
        [[5, 336, 160, 0.25, 2]],
        [[5, 480, 160, 0, 1],
         [5, 480, 160, 0.25, 1],
         [5, 480, 160, 0, 1],
         [5, 480, 160, 0.25, 1]],
    ]
    return build_model_with_cfg(
        RepGhostNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(),
        cfgs=cfgs, width=width,
        **kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem', 'classifier': 'classifier',
        'license': 'mit',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'repghostnet_050.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_058.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_080.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_100.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_111.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_130.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_150.in1k': _cfg(hf_hub_id='timm/'),
    'repghostnet_200.in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def repghostnet_050(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_050', width=0.5, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_058(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_058', width=0.58, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_080(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_080', width=0.8, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_100(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_100', width=1.0, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_111(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_111', width=1.11, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_130(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_130', width=1.3, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_150(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_150', width=1.5, pretrained=pretrained, **kwargs)


@register_model
def repghostnet_200(pretrained=False, **kwargs) -> RepGhostNet:
    return _create_repghostnet('repghostnet_200', width=2.0, pretrained=pretrained, **kwargs)
