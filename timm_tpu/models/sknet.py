"""SKNet: Selective-Kernel ResNets, TPU-native NHWC
(reference: timm/models/sknet.py:1-270; Li et al. 2019).

ResNet trunk with the 3x3 conv replaced by a SelectiveKernel mixer
(timm_tpu/layers/selective_kernel.py).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, ConvNormAct, SelectiveKernel, get_act_fn
from ..layers.drop import DropPath
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .resnet import ResNet, checkpoint_filter_fn

__all__ = ['SelectiveKernelBasic', 'SelectiveKernelBottleneck']


class SelectiveKernelBasic(nnx.Module):
    """(reference sknet.py:24-100)."""
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, cardinality=1,
                 base_width=64, sk_kwargs=None, reduce_first=1, dilation=1,
                 first_dilation=None, act_layer='relu', norm_layer: Callable = BatchNormAct2d,
                 attn_layer=None, aa_layer=None, drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        sk_kwargs = sk_kwargs or {}
        assert aa_layer is None, 'aa_layer not supported by SelectiveKernelBasic'
        assert cardinality == 1 and base_width == 64
        first_planes = planes // reduce_first
        outplanes = planes * self.expansion
        first_dilation = first_dilation or dilation
        kw = dict(act_layer=act_layer, norm_layer=norm_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = SelectiveKernel(
            inplanes, first_planes, stride=stride, dilation=first_dilation, **sk_kwargs, **kw)
        self.conv2 = ConvNormAct(
            first_planes, outplanes, kernel_size=3, dilation=dilation, apply_act=False, **kw)
        self.se = attn_layer(outplanes, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if attn_layer else None
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.conv2.bn, 'scale'):
            self.conv2.bn.scale[...] = jnp.zeros_like(self.conv2.bn.scale[...])

    def __call__(self, x):
        shortcut = x
        x = self.conv1(x)
        x = self.conv2(x)
        if self.se is not None:
            x = self.se(x)
        x = self.drop_path(x)
        if self.downsample is not None:
            shortcut = self.downsample(shortcut)
        return self.act(x + shortcut)


class SelectiveKernelBottleneck(nnx.Module):
    """(reference sknet.py:103-176)."""
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, cardinality=1,
                 base_width=64, sk_kwargs=None, reduce_first=1, dilation=1,
                 first_dilation=None, act_layer='relu', norm_layer: Callable = BatchNormAct2d,
                 attn_layer=None, aa_layer=None, drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        sk_kwargs = sk_kwargs or {}
        assert aa_layer is None, 'aa_layer not supported by SelectiveKernelBottleneck'
        width = int(math.floor(planes * (base_width / 64)) * cardinality)
        first_planes = width // reduce_first
        outplanes = planes * self.expansion
        first_dilation = first_dilation or dilation
        kw = dict(act_layer=act_layer, norm_layer=norm_layer,
                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNormAct(inplanes, first_planes, kernel_size=1, **kw)
        self.conv2 = SelectiveKernel(
            first_planes, width, stride=stride, dilation=first_dilation,
            groups=cardinality, **sk_kwargs, **kw)
        self.conv3 = ConvNormAct(width, outplanes, kernel_size=1, apply_act=False, **kw)
        self.se = attn_layer(outplanes, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if attn_layer else None
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.conv3.bn, 'scale'):
            self.conv3.bn.scale[...] = jnp.zeros_like(self.conv3.bn.scale[...])

    def __call__(self, x):
        shortcut = x
        x = self.conv1(x)
        x = self.conv2(x)
        x = self.conv3(x)
        if self.se is not None:
            x = self.se(x)
        x = self.drop_path(x)
        if self.downsample is not None:
            shortcut = self.downsample(shortcut)
        return self.act(x + shortcut)


def _create_skresnet(variant, pretrained=False, **kwargs):
    block_args = kwargs.pop('block_args', {})
    block = kwargs.pop('block')
    expansion = block.expansion
    if block_args:
        block = partial(block, **block_args)
        block.expansion = expansion
    return build_model_with_cfg(
        ResNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        block=block,
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv1', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'skresnet18.ra_in1k': _cfg(hf_hub_id='timm/'),
    'skresnet34.ra_in1k': _cfg(hf_hub_id='timm/'),
    'skresnet50.untrained': _cfg(),
    'skresnet50d.untrained': _cfg(first_conv='conv1.0'),
    'skresnext50_32x4d.ra_in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def skresnet18(pretrained=False, **kwargs) -> ResNet:
    sk_kwargs = dict(rd_ratio=1 / 8, rd_divisor=16, split_input=True)
    model_args = dict(
        block=SelectiveKernelBasic, layers=(2, 2, 2, 2), block_args=dict(sk_kwargs=sk_kwargs),
        zero_init_last=False)
    return _create_skresnet('skresnet18', pretrained, **dict(model_args, **kwargs))


@register_model
def skresnet34(pretrained=False, **kwargs) -> ResNet:
    sk_kwargs = dict(rd_ratio=1 / 8, rd_divisor=16, split_input=True)
    model_args = dict(
        block=SelectiveKernelBasic, layers=(3, 4, 6, 3), block_args=dict(sk_kwargs=sk_kwargs),
        zero_init_last=False)
    return _create_skresnet('skresnet34', pretrained, **dict(model_args, **kwargs))


@register_model
def skresnet50(pretrained=False, **kwargs) -> ResNet:
    sk_kwargs = dict(split_input=True)
    model_args = dict(
        block=SelectiveKernelBottleneck, layers=(3, 4, 6, 3), block_args=dict(sk_kwargs=sk_kwargs),
        zero_init_last=False)
    return _create_skresnet('skresnet50', pretrained, **dict(model_args, **kwargs))


@register_model
def skresnet50d(pretrained=False, **kwargs) -> ResNet:
    sk_kwargs = dict(split_input=True)
    model_args = dict(
        block=SelectiveKernelBottleneck, layers=(3, 4, 6, 3), stem_width=32, stem_type='deep',
        avg_down=True, block_args=dict(sk_kwargs=sk_kwargs), zero_init_last=False)
    return _create_skresnet('skresnet50d', pretrained, **dict(model_args, **kwargs))


@register_model
def skresnext50_32x4d(pretrained=False, **kwargs) -> ResNet:
    sk_kwargs = dict(rd_ratio=1 / 16, rd_divisor=32, split_input=False)
    model_args = dict(
        block=SelectiveKernelBottleneck, layers=(3, 4, 6, 3), cardinality=32, base_width=4,
        block_args=dict(sk_kwargs=sk_kwargs), zero_init_last=False)
    return _create_skresnet('skresnext50_32x4d', pretrained, **dict(model_args, **kwargs))
