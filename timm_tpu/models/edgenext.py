"""EdgeNeXt, TPU-native NHWC
(reference: timm/models/edgenext.py:1-712; Maaz et al. 2022).

ConvNeXt-style local blocks + Split-Transpose global blocks: a Res2Net-like
depthwise cascade over channel splits followed by cross-covariance (channel)
attention. Reuses XCiT's Fourier positional encoding.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    ClassifierHead, DropPath, Dropout, LayerNorm, Mlp, NormMlpClassifierHead,
    calculate_drop_path_rates, create_conv2d, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model
from .xcit import PositionalEncodingFourier

__all__ = ['EdgeNeXt']


class ConvBlock(nnx.Module):
    """ConvNeXt-style block w/ optional down-stride (reference edgenext.py:84)."""

    def __init__(self, dim, dim_out=None, kernel_size=7, stride=1, conv_bias=True,
                 expand_ratio=4.0, ls_init_value=1e-6, act_layer='gelu', drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        dim_out = dim_out or dim
        self.shortcut_after_dw = stride > 1 or dim != dim_out
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv_dw = create_conv2d(
            dim, dim_out, kernel_size=kernel_size, stride=stride, depthwise=True,
            bias=conv_bias, **kw)
        self.norm = LayerNorm(dim_out, eps=1e-6, rngs=rngs)
        self.mlp = Mlp(dim_out, int(expand_ratio * dim_out), act_layer=act_layer, **kw)
        self.gamma = nnx.Param(jnp.full((dim_out,), ls_init_value, param_dtype)) \
            if ls_init_value > 0 else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        shortcut = x
        x = self.conv_dw(x)
        if self.shortcut_after_dw:
            shortcut = x
        x = self.mlp(self.norm(x))
        if self.gamma is not None:
            x = self.gamma[...].astype(x.dtype) * x
        return shortcut + self.drop_path(x)


class CrossCovarianceAttn(nnx.Module):
    """Channel (C x C) attention (reference edgenext.py:141)."""

    def __init__(self, dim, num_heads=8, qkv_bias=False, attn_drop=0.0, proj_drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.temperature = nnx.Param(jnp.ones((num_heads, 1, 1), param_dtype))
        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, dim * 3, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, N, C = x.shape
        d = C // self.num_heads
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, d).transpose(2, 0, 3, 4, 1)
        q, k, v = qkv[0], qkv[1], qkv[2]  # (B, h, d, N)
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
        k = k / jnp.maximum(jnp.linalg.norm(k, axis=-1, keepdims=True), 1e-12)
        attn = jnp.einsum('bhdn,bhen->bhde', q, k) * self.temperature[...].astype(q.dtype)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        x = jnp.einsum('bhde,bhen->bhdn', attn, v)
        x = x.transpose(0, 3, 1, 2).reshape(B, N, C)
        return self.proj_drop(self.proj(x))

    def no_weight_decay(self):
        return {'temperature'}


class SplitTransposeBlock(nnx.Module):
    """Res2Net-style split conv cascade + XCA + MLP (reference edgenext.py:183)."""

    def __init__(self, dim, num_scales=1, num_heads=8, expand_ratio=4.0, use_pos_emb=True,
                 conv_bias=True, qkv_bias=True, ls_init_value=1e-6, act_layer='gelu',
                 drop_path=0.0, attn_drop=0.0, proj_drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        width = max(int(math.ceil(dim / num_scales)), int(math.floor(dim // num_scales)))
        self.width = width
        self.num_scales = max(1, num_scales - 1)
        self.dim = dim
        self.convs = nnx.List([
            create_conv2d(width, width, kernel_size=3, depthwise=True, bias=conv_bias, **kw)
            for _ in range(self.num_scales)
        ])
        self.pos_embd = PositionalEncodingFourier(dim=dim, **kw) if use_pos_emb else None
        self.norm_xca = LayerNorm(dim, eps=1e-6, rngs=rngs)
        self.gamma_xca = nnx.Param(jnp.full((dim,), ls_init_value, param_dtype)) \
            if ls_init_value > 0 else None
        self.xca = CrossCovarianceAttn(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, attn_drop=attn_drop,
            proj_drop=proj_drop, **kw)
        self.norm = LayerNorm(dim, eps=1e-6, rngs=rngs)
        self.mlp = Mlp(dim, int(expand_ratio * dim), act_layer=act_layer, **kw)
        self.gamma = nnx.Param(jnp.full((dim,), ls_init_value, param_dtype)) \
            if ls_init_value > 0 else None
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        shortcut = x
        B, H, W, C = x.shape
        # torch chunk(n) may yield a short last chunk; channel dims here are
        # sized so the even split matches the reference
        n_chunks = len(self.convs) + 1
        chunk = -(-C // n_chunks)
        spx = [x[..., i * chunk:(i + 1) * chunk] for i in range(n_chunks)]
        spo = []
        sp = spx[0]
        for i, conv in enumerate(self.convs):
            if i > 0:
                sp = sp + spx[i]
            sp = conv(sp)
            spo.append(sp)
        spo.append(spx[-1])
        x = jnp.concatenate(spo, axis=-1)

        x = x.reshape(B, H * W, C)
        if self.pos_embd is not None:
            pos = self.pos_embd(H, W).reshape(1, -1, C)
            x = x + pos.astype(x.dtype)
        y = self.xca(self.norm_xca(x))
        if self.gamma_xca is not None:
            y = self.gamma_xca[...].astype(y.dtype) * y
        x = x + self.drop_path(y)
        x = x.reshape(B, H, W, C)

        y = self.mlp(self.norm(x))
        if self.gamma is not None:
            y = self.gamma[...].astype(y.dtype) * y
        return shortcut + self.drop_path(y)


class _DownsampleNormConv(nnx.Module):
    def __init__(self, in_chs, out_chs, conv_bias, *, dtype=None, param_dtype=jnp.float32, rngs):
        self.norm = LayerNorm(in_chs, eps=1e-6, rngs=rngs)
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(2, 2), strides=2, padding='VALID', use_bias=conv_bias,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.conv(self.norm(x))


class EdgeNeXtStage(nnx.Module):
    def __init__(self, in_chs, out_chs, stride=2, depth=2, num_global_blocks=1,
                 num_heads=4, scales=2, kernel_size=7, expand_ratio=4.0,
                 use_pos_emb=False, downsample_block=False, conv_bias=True,
                 ls_init_value=1.0, drop_path_rates=None, act_layer='gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        if downsample_block or stride == 1:
            self.downsample = None
        else:
            self.downsample = _DownsampleNormConv(in_chs, out_chs, conv_bias, **kw)
            in_chs = out_chs
        blocks = []
        for i in range(depth):
            if i < depth - num_global_blocks:
                blocks.append(ConvBlock(
                    dim=in_chs, dim_out=out_chs,
                    stride=stride if downsample_block and i == 0 else 1,
                    conv_bias=conv_bias, kernel_size=kernel_size,
                    expand_ratio=expand_ratio, ls_init_value=ls_init_value,
                    act_layer=act_layer, drop_path=drop_path_rates[i], **kw))
            else:
                blocks.append(SplitTransposeBlock(
                    dim=in_chs, num_scales=scales, num_heads=num_heads,
                    expand_ratio=expand_ratio, use_pos_emb=use_pos_emb,
                    conv_bias=conv_bias, ls_init_value=ls_init_value,
                    drop_path=drop_path_rates[i], act_layer=act_layer, **kw))
            in_chs = out_chs
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class _Stem(nnx.Module):
    def __init__(self, in_chans, dim, stem_type, conv_bias, *, dtype=None,
                 param_dtype=jnp.float32, rngs):
        if stem_type == 'patch':
            self.conv = nnx.Conv(in_chans, dim, kernel_size=(4, 4), strides=4, padding='VALID',
                                 use_bias=conv_bias, kernel_init=trunc_normal_(std=0.02),
                                 bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:  # overlap
            self.conv = nnx.Conv(in_chans, dim, kernel_size=(9, 9), strides=4,
                                 padding=[(4, 4), (4, 4)], use_bias=conv_bias,
                                 kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = LayerNorm(dim, eps=1e-6, rngs=rngs)

    def __call__(self, x):
        return self.norm(self.conv(x))


class EdgeNeXt(nnx.Module):
    """EdgeNeXt with the reference's model contract (reference edgenext.py:355-560)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            dims: Tuple[int, ...] = (24, 48, 88, 168),
            depths: Tuple[int, ...] = (3, 3, 9, 3),
            global_block_counts: Tuple[int, ...] = (0, 1, 1, 1),
            kernel_sizes: Tuple[int, ...] = (3, 5, 7, 9),
            heads: Tuple[int, ...] = (8, 8, 8, 8),
            d2_scales: Tuple[int, ...] = (2, 2, 3, 4),
            use_pos_emb: Tuple[bool, ...] = (False, True, False, False),
            ls_init_value: float = 1e-6,
            head_init_scale: float = 1.0,
            expand_ratio: float = 4.0,
            downsample_block: bool = False,
            conv_bias: bool = True,
            stem_type: str = 'patch',
            head_norm_first: bool = False,
            act_layer: str = 'gelu',
            drop_path_rate: float = 0.0,
            drop_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert stem_type in ('patch', 'overlap')
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.drop_rate = drop_rate
        self.feature_info = []
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.stem = _Stem(in_chans, dims[0], stem_type, conv_bias, **kw)
        curr_stride = 4
        dp_rates = calculate_drop_path_rates(drop_path_rate, list(depths), stagewise=True)
        stages = []
        in_chs = dims[0]
        for i in range(4):
            stride = 2 if curr_stride == 2 or i > 0 else 1
            curr_stride *= stride
            stages.append(EdgeNeXtStage(
                in_chs=in_chs, out_chs=dims[i], stride=stride, depth=depths[i],
                num_global_blocks=global_block_counts[i], num_heads=heads[i],
                drop_path_rates=dp_rates[i], scales=d2_scales[i],
                expand_ratio=expand_ratio, kernel_size=kernel_sizes[i],
                use_pos_emb=use_pos_emb[i], ls_init_value=ls_init_value,
                downsample_block=downsample_block, conv_bias=conv_bias,
                act_layer=act_layer, **kw))
            in_chs = dims[i]
            self.feature_info += [dict(num_chs=in_chs, reduction=curr_stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = dims[-1]
        if head_norm_first:
            self.norm_pre = LayerNorm(self.num_features, eps=1e-6, rngs=rngs)
            self.head = ClassifierHead(
                self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate, **kw)
        else:
            self.norm_pre = None
            self.head = NormMlpClassifierHead(
                self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
                norm_layer=partial(LayerNorm, eps=1e-6), **kw)
        if head_init_scale != 1.0 and self.head.fc is not None:
            self.head.fc.kernel[...] = self.head.fc.kernel[...] * head_init_scale
            self.head.fc.bias[...] = self.head.fc.bias[...] * head_init_scale

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'temperature'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=r'^stages\.(\d+)' if coarse else [
                (r'^stages\.(\d+)\.downsample', (0,)),
                (r'^stages\.(\d+)\.blocks\.(\d+)', None),
                (r'^norm_pre', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self.stem(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_norm:
            self.norm_pre = None
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    import re
    out = {}
    for k, v in state_dict.items():
        # torch Sequentials: stem.{0,1}, stages.N.downsample.{0,1}
        k = re.sub(r'^stem\.0\.', 'stem.conv.', k)
        k = re.sub(r'^stem\.1\.', 'stem.norm.', k)
        k = re.sub(r'\.downsample\.0\.', '.downsample.norm.', k)
        k = re.sub(r'\.downsample\.1\.', '.downsample.conv.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_edgenext(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        EdgeNeXt, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3)),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 256, 256), 'pool_size': (8, 8),
        'crop_pct': 0.9, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.conv', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'edgenext_xx_small.in1k': _cfg(hf_hub_id='timm/', test_input_size=(3, 288, 288), test_crop_pct=1.0),
    'edgenext_x_small.in1k': _cfg(hf_hub_id='timm/', test_input_size=(3, 288, 288), test_crop_pct=1.0),
    'edgenext_small.usi_in1k': _cfg(
        hf_hub_id='timm/', crop_pct=0.95, test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'edgenext_base.usi_in1k': _cfg(
        hf_hub_id='timm/', crop_pct=0.95, test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'edgenext_small_rw.sw_in1k': _cfg(
        hf_hub_id='timm/', test_input_size=(3, 320, 320), test_crop_pct=1.0),
})


@register_model
def edgenext_xx_small(pretrained=False, **kwargs) -> EdgeNeXt:
    model_args = dict(depths=(2, 2, 6, 2), dims=(24, 48, 88, 168), heads=(4, 4, 4, 4))
    return _create_edgenext('edgenext_xx_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def edgenext_x_small(pretrained=False, **kwargs) -> EdgeNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(32, 64, 100, 192), heads=(4, 4, 4, 4))
    return _create_edgenext('edgenext_x_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def edgenext_small(pretrained=False, **kwargs) -> EdgeNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(48, 96, 160, 304))
    return _create_edgenext('edgenext_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def edgenext_base(pretrained=False, **kwargs) -> EdgeNeXt:
    model_args = dict(depths=(3, 3, 9, 3), dims=(80, 160, 288, 584))
    return _create_edgenext('edgenext_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def edgenext_small_rw(pretrained=False, **kwargs) -> EdgeNeXt:
    model_args = dict(
        depths=(3, 3, 9, 3), dims=(48, 96, 192, 384),
        downsample_block=True, conv_bias=False, stem_type='overlap')
    return _create_edgenext('edgenext_small_rw', pretrained=pretrained, **dict(model_args, **kwargs))
