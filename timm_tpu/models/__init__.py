from ._builder import build_model_with_cfg, load_pretrained, resolve_pretrained_cfg
from ._factory import create_model, parse_model_name, safe_model_name
from ._features import FeatureGetterNet, FeatureInfo, feature_take_indices
from ._helpers import (
    clean_state_dict, load_checkpoint, load_state_dict, load_state_dict_into_model,
    model_state_dict, remap_state_dict, save_state_dict,
)
from ._manipulate import checkpoint_seq, group_parameters, group_with_matcher, named_parameters
from ._pretrained import DefaultCfg, PretrainedCfg
from ._registry import (
    generate_default_cfgs, get_arch_name, get_pretrained_cfg, get_pretrained_cfg_value,
    is_model, is_model_in_modules, is_model_pretrained, list_models, list_modules,
    list_pretrained, model_entrypoint, register_model, split_model_name_tag,
)

from .beit import Beit
from .byoanet import *  # noqa: F401,F403 — registers byoanet entrypoints
from .byobnet import ByoBlockCfg, ByoModelCfg, ByobNet
from .cait import Cait
from .convnext import ConvNeXt
from .deit import VisionTransformerDistilled
from .densenet import DenseNet
from .dpn import DPN
from .edgenext import EdgeNeXt
from .efficientformer import EfficientFormer
from .efficientformer_v2 import EfficientFormerV2
from .efficientnet import EfficientNet
from .eva import Eva
from .ghostnet import GhostNet
from .inception_v3 import InceptionV3
from .levit import Levit, LevitDistilled
from .mambaout import MambaOut
from .maxxvit import MaxxVit, MaxxVitCfg
from .metaformer import MetaFormer
from .mlp_mixer import MlpMixer
from .mobilenetv3 import MobileNetV3
from .mobilevit import *  # noqa: F401,F403 — registers mobilevit entrypoints
from .mvitv2 import MultiScaleVit, MultiScaleVitCfg
from .naflexvit import NaFlexVit
from .nfnet import NfCfg, NormFreeNet
from .regnet import RegNet
from .repvit import RepVit
from .res2net import Bottle2neck
from .resnest import ResNestBottleneck
from .resnet import ResNet
from .rexnet import RexNet
from .sknet import SelectiveKernelBasic, SelectiveKernelBottleneck
from .resnetv2 import ResNetV2
from .swin_transformer import SwinTransformer
from .tiny_vit import TinyVit
from .swin_transformer_v2 import SwinTransformerV2
from .twins import Twins
from .vgg import VGG
from .volo import VOLO
from .xcit import Xcit
from .vision_transformer import VisionTransformer
from .vision_transformer_hybrid import *  # noqa: F401,F403 — registers hybrid vit entrypoints
from .convmixer import ConvMixer
from .hardcorenas import *  # noqa: F401,F403 — registers hardcorenas entrypoints
from .starnet import StarNet
from .xception import Xception
from .pvt_v2 import PyramidVisionTransformerV2
from .repghost import RepGhostNet
from .vovnet import VovNet
from .pit import PoolingVisionTransformer
from .inception_v4 import InceptionV4
