"""Swin Transformer, TPU-native NHWC
(reference: timm/models/swin_transformer.py:1-1255).

Shifted windows are static `jnp.roll`s and the shift attention masks are
precomputed numpy constants per (resolution, window, shift) — everything under
jit is fixed-shape, branch-free. Window partition is a reshape/transpose pair
that XLA fuses into the attention matmuls.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from ..layers import (
    ClassifierHead, DropPath, Dropout, LayerNorm, Mlp, PatchEmbed,
    calculate_drop_path_rates, get_norm_layer, to_2tuple, trunc_normal_, zeros_,
)
from ..layers.attention import scaled_dot_product_attention
from ..layers.drop import dropout_rng_key
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, resolve_stage_scan, scan_stage_stack,
    warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['SwinTransformer', 'SwinTransformerBlock', 'WindowAttention']


def window_partition(x, window_size: Tuple[int, int]):
    """(B, H, W, C) → (B*nW, wh*ww, C)."""
    B, H, W, C = x.shape
    wh, ww = window_size
    x = x.reshape(B, H // wh, wh, W // ww, ww, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(-1, wh * ww, C)


def window_reverse(windows, window_size: Tuple[int, int], H: int, W: int):
    """(B*nW, wh*ww, C) → (B, H, W, C)."""
    wh, ww = window_size
    C = windows.shape[-1]
    B = windows.shape[0] // (H * W // wh // ww)
    x = windows.reshape(B, H // wh, W // ww, wh, ww, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H, W, C)


def _relative_position_index(win_h: int, win_w: int) -> np.ndarray:
    """Static (wh*ww, wh*ww) index into the rel-bias table."""
    coords = np.stack(np.meshgrid(np.arange(win_h), np.arange(win_w), indexing='ij'))
    coords_flat = coords.reshape(2, -1)
    relative = coords_flat[:, :, None] - coords_flat[:, None, :]
    relative = relative.transpose(1, 2, 0)
    relative[:, :, 0] += win_h - 1
    relative[:, :, 1] += win_w - 1
    relative[:, :, 0] *= 2 * win_w - 1
    return relative.sum(-1)


def _shift_attn_mask(H: int, W: int, window_size: Tuple[int, int], shift_size: Tuple[int, int]) -> np.ndarray:
    """Static additive mask (nW, N, N) for shifted windows (reference swin mask)."""
    wh, ww = window_size
    sh, sw = shift_size
    img_mask = np.zeros((1, H, W, 1), np.float32)
    cnt = 0
    for h in (slice(0, -wh), slice(-wh, -sh), slice(-sh, None)):
        for w in (slice(0, -ww), slice(-ww, -sw), slice(-sw, None)):
            img_mask[:, h, w, :] = cnt
            cnt += 1
    mask_windows = img_mask.reshape(1, H // wh, wh, W // ww, ww, 1)
    mask_windows = mask_windows.transpose(0, 1, 3, 2, 4, 5).reshape(-1, wh * ww)
    attn_mask = mask_windows[:, None, :] - mask_windows[:, :, None]
    return np.where(attn_mask != 0, -100.0, 0.0).astype(np.float32)


class WindowAttention(nnx.Module):
    """Window MHSA w/ relative position bias (reference swin WindowAttention)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            head_dim: Optional[int] = None,
            window_size: Union[int, Tuple[int, int]] = 7,
            qkv_bias: bool = True,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.dim = dim
        self.window_size = to_2tuple(window_size)
        win_h, win_w = self.window_size
        self.window_area = win_h * win_w
        self.num_heads = num_heads
        head_dim = head_dim or dim // num_heads
        attn_dim = head_dim * num_heads
        self.head_dim = head_dim
        self.scale = head_dim ** -0.5

        self.relative_position_bias_table = nnx.Param(
            trunc_normal_(std=0.02)(
                rngs.params(), ((2 * win_h - 1) * (2 * win_w - 1), num_heads), param_dtype))
        # nnx.Variable: a raw array attribute breaks nnx graph traversal on
        # older flax (split/state reject array leaves); a Variable is
        # traversal-safe on every version and stays out of the Param state
        self._rel_index = nnx.Variable(jnp.asarray(_relative_position_index(win_h, win_w)))

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, attn_dim * 3, use_bias=qkv_bias)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(attn_dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def _bias(self, dtype):
        table = self.relative_position_bias_table[...]
        bias = table[self._rel_index[...].reshape(-1)]
        bias = bias.reshape(self.window_area, self.window_area, -1).transpose(2, 0, 1)
        return bias[None].astype(dtype)  # (1, H, N, N)

    def __call__(self, x, mask=None):
        # x: (B_windows, N, C); mask: (nW, N, N) additive or None
        Bw, N, C = x.shape
        qkv = self.qkv(x).reshape(Bw, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        attn_bias = self._bias(jnp.float32)
        if mask is not None:
            nW = mask.shape[0]
            mask_f = mask[None, :, None, :, :]  # (1, nW, 1, N, N)
            attn_bias = attn_bias[None] + mask_f  # (1|B, nW, H, N, N) broadcast
            # fold window dim back into batch for the attention call
            attn_bias = jnp.broadcast_to(
                attn_bias, (Bw // nW, nW, self.num_heads, N, N)).reshape(Bw, self.num_heads, N, N)
        else:
            attn_bias = jnp.broadcast_to(attn_bias, (Bw, self.num_heads, N, N))
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, dropout_p=dropout_p, dropout_key=dropout_key,
            scale=self.scale, fused=False)
        x = x.transpose(0, 2, 1, 3).reshape(Bw, N, -1)
        x = self.proj(x)
        return self.proj_drop(x)


class SwinTransformerBlock(nnx.Module):
    def __init__(
            self,
            dim: int,
            input_resolution: Tuple[int, int],
            num_heads: int = 4,
            head_dim: Optional[int] = None,
            window_size: int = 7,
            shift_size: int = 0,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.dim = dim
        self.input_resolution = input_resolution
        ws, ss = self._calc_window_shift(to_2tuple(window_size), to_2tuple(shift_size))
        self.window_size = ws
        self.shift_size = ss
        self.window_area = ws[0] * ws[1]

        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = WindowAttention(
            dim, num_heads=num_heads, head_dim=head_dim, window_size=ws,
            qkv_bias=qkv_bias, attn_drop=attn_drop, proj_drop=proj_drop,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = Mlp(dim, int(dim * mlp_ratio), act_layer=act_layer, drop=proj_drop,
                       dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

        if any(self.shift_size):
            H, W = input_resolution
            self._attn_mask = nnx.Variable(jnp.asarray(_shift_attn_mask(H, W, ws, ss)))
        else:
            self._attn_mask = None

    def _calc_window_shift(self, target_window, target_shift):
        # window can't exceed resolution, and must divide it (static shapes —
        # we shrink to the largest divisor instead of the reference's padding;
        # identical for all standard 224/384 configs where 7|56,28,14)
        ws, ss = [], []
        for r, w, s in zip(self.input_resolution, target_window, target_shift):
            if r <= w:
                ws.append(r)
                ss.append(0)
            else:
                while r % w:
                    w -= 1
                ws.append(w)
                ss.append(min(s, w // 2))
        return tuple(ws), tuple(ss)

    def _attn(self, x):
        B, H, W, C = x.shape
        sh, sw = self.shift_size
        if sh or sw:
            x = jnp.roll(x, shift=(-sh, -sw), axis=(1, 2))
        xw = window_partition(x, self.window_size)
        xw = self.attn(xw, mask=None if self._attn_mask is None else self._attn_mask[...])
        x = window_reverse(xw, self.window_size, H, W)
        if sh or sw:
            x = jnp.roll(x, shift=(sh, sw), axis=(1, 2))
        return x

    def __call__(self, x):
        x = x + self.drop_path1(self._attn(self.norm1(x)))
        x = x + self.drop_path2(self.mlp(self.norm2(x)))
        return x


class PatchMerging(nnx.Module):
    def __init__(self, dim: int, out_dim: Optional[int] = None, norm_layer: Callable = LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.dim = dim
        self.out_dim = out_dim or 2 * dim
        self.norm = norm_layer(4 * dim, rngs=rngs)
        self.reduction = nnx.Linear(
            4 * dim, self.out_dim, use_bias=False, kernel_init=trunc_normal_(std=0.02),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        x = x.reshape(B, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 4, 2, 5).reshape(B, H // 2, W // 2, 4 * C)
        return self.reduction(self.norm(x))


class SwinTransformerStage(nnx.Module):
    def __init__(
            self,
            dim: int,
            out_dim: int,
            input_resolution: Tuple[int, int],
            depth: int,
            downsample: bool = True,
            num_heads: int = 4,
            head_dim: Optional[int] = None,
            window_size: int = 7,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: Union[List[float], float] = 0.0,
            norm_layer: Callable = LayerNorm,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.grad_checkpointing = False
        self.stage_scan = False
        if downsample:
            self.downsample = PatchMerging(dim, out_dim, norm_layer=norm_layer,
                                           dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            output_resolution = (input_resolution[0] // 2, input_resolution[1] // 2)
        else:
            self.downsample = None
            output_resolution = input_resolution
        self.output_resolution = output_resolution

        if isinstance(drop_path, float):
            drop_path = [drop_path] * depth
        shift = window_size // 2
        self.blocks = nnx.List([
            SwinTransformerBlock(
                out_dim,
                input_resolution=output_resolution,
                num_heads=num_heads,
                head_dim=head_dim,
                window_size=window_size,
                shift_size=0 if i % 2 == 0 else shift,
                mlp_ratio=mlp_ratio,
                qkv_bias=qkv_bias,
                proj_drop=proj_drop,
                attn_drop=attn_drop,
                drop_path=drop_path[i],
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        if self.stage_scan:
            try:
                return scan_stage_stack(self.blocks, x, remat=self.grad_checkpointing)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e, what='stage_scan')
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class SwinTransformer(nnx.Module):
    def __init__(
            self,
            img_size: int = 224,
            patch_size: int = 4,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dim: int = 96,
            depths: Tuple[int, ...] = (2, 2, 6, 2),
            num_heads: Tuple[int, ...] = (3, 6, 12, 24),
            head_dim: Optional[int] = None,
            window_size: int = 7,
            mlp_ratio: float = 4.0,
            qkv_bias: bool = True,
            drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.1,
            norm_layer: Optional[Union[str, Callable]] = None,
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # reference uses torch nn.LayerNorm default eps (1e-5)
        norm_layer = get_norm_layer(norm_layer) or partial(LayerNorm, eps=1e-5)
        self.num_classes = num_classes
        num_layers = len(depths)
        self.num_features = self.head_hidden_size = int(embed_dim * 2 ** (num_layers - 1))

        self.patch_embed = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans,
            embed_dim=embed_dim, norm_layer=norm_layer, flatten=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        grid = self.patch_embed.grid_size

        dpr = calculate_drop_path_rates(drop_path_rate, list(depths), stagewise=True)
        stages = []
        in_dim = embed_dim
        in_res = grid
        self.feature_info = []
        scale = 1
        for i in range(num_layers):
            out_dim = int(embed_dim * 2 ** i)
            downsample = i > 0
            stages.append(SwinTransformerStage(
                dim=in_dim,
                out_dim=out_dim,
                input_resolution=in_res,
                depth=depths[i],
                downsample=downsample,
                num_heads=num_heads[i],
                head_dim=head_dim,
                window_size=window_size,
                mlp_ratio=mlp_ratio,
                qkv_bias=qkv_bias,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            ))
            in_dim = out_dim
            if downsample:
                in_res = (in_res[0] // 2, in_res[1] // 2)
                scale *= 2
            self.feature_info += [dict(num_chs=out_dim, reduction=patch_size * scale, module=f'layers.{i}')]
        self.layers = nnx.List(stages)
        self.set_stage_scan(resolve_stage_scan(stage_scan))

        self.norm = norm_layer(self.num_features, rngs=rngs)
        self.head = ClassifierHead(
            self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'relative_position_bias_table'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^patch_embed',
            blocks=r'^layers\.(\d+)' if coarse else [
                (r'^layers\.(\d+).downsample', (0,)),
                (r'^layers\.(\d+)\.blocks\.(\d+)', None),
                (r'^norm', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for l in self.layers:
            l.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        for s in self.layers:
            s.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        for stage in self.layers:
            x = stage(x)
        return self.norm(x) if self.norm is not None else x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.layers), indices)
        x = self.patch_embed(x)
        intermediates = []
        stages = self.layers if not stop_early else list(self.layers)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(
                    self.norm(x) if (norm and self.norm is not None and i == len(self.layers) - 1) else x)
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.layers), indices)
        self.layers = nnx.List(list(self.layers)[:max_index + 1])
        if prune_norm:
            self.norm = None  # sized for the unpruned width; drop with the tail
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'swin_tiny_patch4_window7_224.ms_in1k': _cfg(hf_hub_id='timm/'),
    'swin_small_patch4_window7_224.ms_in1k': _cfg(hf_hub_id='timm/'),
    'swin_base_patch4_window7_224.ms_in1k': _cfg(hf_hub_id='timm/'),
    'swin_large_patch4_window7_224.ms_in22k_ft_in1k': _cfg(hf_hub_id='timm/'),
    'test_swin.untrained': _cfg(input_size=(3, 96, 96)),
})


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    out = {k: v for k, v in state_dict.items()
           if not k.endswith(('relative_position_index', 'attn_mask'))}
    return convert_torch_state_dict(out, model)


def _create_swin(variant: str, pretrained: bool = False, **kwargs) -> SwinTransformer:
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        SwinTransformer, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def swin_tiny_patch4_window7_224(pretrained=False, **kwargs) -> SwinTransformer:
    model_args = dict(patch_size=4, window_size=7, embed_dim=96, depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24))
    return _create_swin('swin_tiny_patch4_window7_224', pretrained, **dict(model_args, **kwargs))


@register_model
def swin_small_patch4_window7_224(pretrained=False, **kwargs) -> SwinTransformer:
    model_args = dict(patch_size=4, window_size=7, embed_dim=96, depths=(2, 2, 18, 2), num_heads=(3, 6, 12, 24))
    return _create_swin('swin_small_patch4_window7_224', pretrained, **dict(model_args, **kwargs))


@register_model
def swin_base_patch4_window7_224(pretrained=False, **kwargs) -> SwinTransformer:
    model_args = dict(patch_size=4, window_size=7, embed_dim=128, depths=(2, 2, 18, 2), num_heads=(4, 8, 16, 32))
    return _create_swin('swin_base_patch4_window7_224', pretrained, **dict(model_args, **kwargs))


@register_model
def swin_large_patch4_window7_224(pretrained=False, **kwargs) -> SwinTransformer:
    model_args = dict(patch_size=4, window_size=7, embed_dim=192, depths=(2, 2, 18, 2), num_heads=(6, 12, 24, 48))
    return _create_swin('swin_large_patch4_window7_224', pretrained, **dict(model_args, **kwargs))


@register_model
def test_swin(pretrained=False, **kwargs) -> SwinTransformer:
    model_args = dict(
        img_size=96, patch_size=4, window_size=4, embed_dim=32, depths=(1, 1, 2), num_heads=(2, 2, 4))
    return _create_swin('test_swin', pretrained, **dict(model_args, **kwargs))
