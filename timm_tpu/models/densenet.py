"""DenseNet (reference: timm/models/densenet.py:1-563), TPU-native NHWC."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, ClassifierHead, create_conv2d
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .resnet import avg_pool2d, max_pool2d

__all__ = ['DenseNet']


class DenseLayer(nnx.Module):
    def __init__(self, in_chs: int, growth_rate: int, bn_size: int = 4,
                 norm_layer: Callable = BatchNormAct2d, drop_rate: float = 0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm1 = norm_layer(in_chs, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = create_conv2d(in_chs, bn_size * growth_rate, 1,
                                   dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm2 = norm_layer(bn_size * growth_rate, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv2 = create_conv2d(bn_size * growth_rate, growth_rate, 3, padding=None,
                                   dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        out = self.conv1(self.norm1(x))
        out = self.conv2(self.norm2(out))
        return jnp.concatenate([x, out], axis=-1)


class DenseTransition(nnx.Module):
    def __init__(self, in_chs: int, out_chs: int, norm_layer: Callable = BatchNormAct2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm = norm_layer(in_chs, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv = create_conv2d(in_chs, out_chs, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        x = self.conv(self.norm(x))
        return avg_pool2d(x, 2, 2)


class DenseNet(nnx.Module):
    def __init__(
            self,
            growth_rate: int = 32,
            block_config: Tuple[int, ...] = (6, 12, 24, 16),
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: str = 'avg',
            bn_size: int = 4,
            stem_type: str = '',
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            drop_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        num_init_features = growth_rate * 2

        self.stem_conv = create_conv2d(in_chans, num_init_features, 7, stride=2, padding=None,
                                       dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stem_norm = norm_layer(num_init_features, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.feature_info = [dict(num_chs=num_init_features, reduction=2, module='stem_norm')]

        blocks = []
        transitions = []
        num_features = num_init_features
        curr_stride = 4
        for i, num_layers in enumerate(block_config):
            layers = []
            for j in range(num_layers):
                layers.append(DenseLayer(
                    num_features + j * growth_rate, growth_rate, bn_size=bn_size,
                    norm_layer=norm_layer, drop_rate=drop_rate,
                    dtype=dtype, param_dtype=param_dtype, rngs=rngs))
            blocks.append(nnx.List(layers))
            num_features = num_features + num_layers * growth_rate
            self.feature_info.append(dict(
                num_chs=num_features, reduction=curr_stride, module=f'denseblock{i + 1}'))
            if i != len(block_config) - 1:
                transitions.append(DenseTransition(
                    num_features, num_features // 2, norm_layer=norm_layer,
                    dtype=dtype, param_dtype=param_dtype, rngs=rngs))
                num_features = num_features // 2
                curr_stride *= 2
        self.blocks = nnx.List(blocks)
        self.transitions = nnx.List(transitions)
        self.final_norm = norm_layer(num_features, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.num_features = self.head_hidden_size = num_features
        self.head = ClassifierHead(
            num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem_', blocks=r'^blocks\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    def _stem(self, x):
        x = self.stem_norm(self.stem_conv(x))
        return max_pool2d(x, 3, 2)

    def forward_features(self, x):
        x = self._stem(x)
        for i, block in enumerate(self.blocks):
            for layer in block:
                x = layer(x)
            if i < len(self.transitions):
                x = self.transitions[i](x)
        return self.final_norm(x)

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        num_stages = len(self.blocks) + 1
        take_indices, max_index = feature_take_indices(num_stages, indices)
        x = self.stem_norm(self.stem_conv(x))
        intermediates = []
        if 0 in take_indices:
            intermediates.append(x)
        x = max_pool2d(x, 3, 2)
        for i, block in enumerate(self.blocks):
            if stop_early and i > max_index - 1:
                break
            for layer in block:
                x = layer(x)
            if (i + 1) in take_indices:
                intermediates.append(x)
            if i < len(self.transitions):
                x = self.transitions[i](x)
        if intermediates_only:
            return intermediates
        x = self.final_norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.blocks) + 1, indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem_conv', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'densenet121.ra_in1k': _cfg(hf_hub_id='timm/'),
    'densenet169.tv_in1k': _cfg(hf_hub_id='timm/'),
    'densenet201.tv_in1k': _cfg(hf_hub_id='timm/'),
    'densenet161.tv_in1k': _cfg(hf_hub_id='timm/'),
})


def checkpoint_filter_fn(state_dict, model):
    """Map reference densenet names (features.denseblockN.denselayerM...)
    onto this module's blocks/transitions layout."""
    import re
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'^features\.conv0\.', 'stem_conv.', k)
        k = re.sub(r'^features\.norm0\.', 'stem_norm.', k)
        m = re.match(r'^features\.denseblock(\d+)\.denselayer(\d+)\.(.*)$', k)
        if m:
            k = f'blocks.{int(m.group(1)) - 1}.{int(m.group(2)) - 1}.{m.group(3)}'
        m = re.match(r'^features\.transition(\d+)\.(.*)$', k)
        if m:
            k = f'transitions.{int(m.group(1)) - 1}.{m.group(2)}'
        k = re.sub(r'^features\.norm5\.', 'final_norm.', k)
        k = re.sub(r'^classifier\.', 'head.fc.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_densenet(variant: str, pretrained: bool = False, **kwargs) -> DenseNet:
    return build_model_with_cfg(
        DenseNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **kwargs,
    )


@register_model
def densenet121(pretrained=False, **kwargs) -> DenseNet:
    model_args = dict(growth_rate=32, block_config=(6, 12, 24, 16))
    return _create_densenet('densenet121', pretrained, **dict(model_args, **kwargs))


@register_model
def densenet169(pretrained=False, **kwargs) -> DenseNet:
    model_args = dict(growth_rate=32, block_config=(6, 12, 32, 32))
    return _create_densenet('densenet169', pretrained, **dict(model_args, **kwargs))


@register_model
def densenet161(pretrained=False, **kwargs) -> DenseNet:
    model_args = dict(growth_rate=48, block_config=(6, 12, 36, 24))
    return _create_densenet('densenet161', pretrained, **dict(model_args, **kwargs))


@register_model
def densenet201(pretrained=False, **kwargs) -> DenseNet:
    model_args = dict(growth_rate=32, block_config=(6, 12, 48, 32))
    return _create_densenet('densenet201', pretrained, **dict(model_args, **kwargs))
