"""StarNet (reference: timm/models/starnet.py:1-362), TPU-native NHWC.

Element-wise-multiplication ("star") blocks: dw 7x7 conv, two parallel 1x1
expansions whose product (act(f1) * f2) forms the mixer, then 1x1 + dw back
down. All convs stay NHWC; the two 1x1 branches are one fused matmul pair on
the MXU.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, DropPath, SelectAdaptivePool2d, calculate_drop_path_rates,
    create_conv2d, get_act_fn, trunc_normal_, zeros_,
)
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['StarNet']


class ConvBN(nnx.Module):
    """conv (+ optional BN) keeping the reference's ``.conv``/``.bn`` names
    (reference starnet.py:28-48)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, padding=0, groups=1,
                 with_bn=True, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = create_conv2d(
            in_chs, out_chs, kernel_size, stride=stride, padding=padding, groups=groups,
            bias=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_chs, rngs=rngs) if with_bn else None

    def __call__(self, x):
        x = self.conv(x)
        if self.bn is not None:
            x = self.bn(x)
        return x


class StarBlock(nnx.Module):
    """(reference starnet.py:51-80)."""

    def __init__(self, dim, mlp_ratio=3, drop_path=0.0, act_layer='relu6',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.dwconv = ConvBN(dim, dim, 7, 1, 3, groups=dim, with_bn=True, **kw)
        self.f1 = ConvBN(dim, mlp_ratio * dim, 1, with_bn=False, **kw)
        self.f2 = ConvBN(dim, mlp_ratio * dim, 1, with_bn=False, **kw)
        self.g = ConvBN(mlp_ratio * dim, dim, 1, with_bn=True, **kw)
        self.dwconv2 = ConvBN(dim, dim, 7, 1, 3, groups=dim, with_bn=False, **kw)
        self.act = get_act_fn(act_layer)
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        residual = x
        x = self.dwconv(x)
        x1, x2 = self.f1(x), self.f2(x)
        x = self.act(x1) * x2
        x = self.dwconv2(self.g(x))
        return residual + self.drop_path(x)


class StarNet(nnx.Module):
    """(reference starnet.py:83-270)."""

    def __init__(
            self,
            base_dim: int = 32,
            depths: Tuple[int, ...] = (3, 3, 12, 5),
            mlp_ratio: int = 4,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            act_layer: Union[str, Callable] = 'relu6',
            num_classes: int = 1000,
            in_chans: int = 3,
            global_pool: str = 'avg',
            output_stride: int = 32,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        self.feature_info = []
        stem_chs = 32

        # stem: ConvBN at Sequential index 0 (act is paramless)
        self.stem = nnx.List([ConvBN(in_chans, stem_chs, 3, stride=2, padding=1, **kw)])
        self.stem_act = get_act_fn(act_layer)
        prev_chs = stem_chs

        dpr = calculate_drop_path_rates(drop_path_rate, sum(depths))
        stages = []
        cur = 0
        for i_layer, depth in enumerate(depths):
            embed_dim = base_dim * 2 ** i_layer
            # stage keeps the reference Sequential layout: index 0 is the
            # downsampler, 1..depth are blocks
            stage = [ConvBN(prev_chs, embed_dim, 3, stride=2, padding=1, **kw)]
            stage += [StarBlock(embed_dim, mlp_ratio, dpr[cur + i], act_layer, **kw) for i in range(depth)]
            cur += depth
            prev_chs = embed_dim
            stages.append(nnx.List(stage))
            self.feature_info.append(dict(
                num_chs=prev_chs, reduction=2 ** (i_layer + 2), module=f'stages.{i_layer}'))
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = prev_chs
        self.norm = BatchNorm2d(self.num_features, rngs=rngs)
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=bool(global_pool))
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem\.\d+',
            blocks=[
                (r'^stages\.(\d+)' if coarse else r'^stages\.(\d+)\.(\d+)', None),
                (r'norm', (99999,)),
            ])

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=bool(global_pool))
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.head_hidden_size, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _stem(self, x):
        return self.stem_act(self.stem[0](x))

    def forward_features(self, x):
        x = self._stem(x)
        for stage in self.stages:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for m in stage:
                    x = m(x)
        return self.norm(x)

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self._stem(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            for m in stage:
                x = m(x)
            if i in take_indices:
                intermediates.append(self.norm(x) if (norm and i == len(self.stages) - 1) else x)
        if intermediates_only:
            return intermediates
        x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    state_dict = state_dict.get('state_dict', state_dict)
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.0.conv', 'classifier': 'head',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'starnet_s1.in1k': _cfg(hf_hub_id='timm/'),
    'starnet_s2.in1k': _cfg(hf_hub_id='timm/'),
    'starnet_s3.in1k': _cfg(hf_hub_id='timm/'),
    'starnet_s4.in1k': _cfg(hf_hub_id='timm/'),
    'starnet_s050.untrained': _cfg(),
    'starnet_s100.untrained': _cfg(),
    'starnet_s150.untrained': _cfg(),
})


def _create_starnet(variant: str, pretrained: bool = False, **kwargs) -> StarNet:
    return build_model_with_cfg(
        StarNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3)),
        **kwargs,
    )


@register_model
def starnet_s1(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=24, depths=[2, 2, 8, 3])
    return _create_starnet('starnet_s1', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s2(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=32, depths=[1, 2, 6, 2])
    return _create_starnet('starnet_s2', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s3(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=32, depths=[2, 2, 8, 4])
    return _create_starnet('starnet_s3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s4(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=32, depths=[3, 3, 12, 5])
    return _create_starnet('starnet_s4', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s050(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=16, depths=[1, 1, 3, 1], mlp_ratio=3)
    return _create_starnet('starnet_s050', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s100(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=20, depths=[1, 2, 4, 1], mlp_ratio=4)
    return _create_starnet('starnet_s100', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def starnet_s150(pretrained: bool = False, **kwargs) -> StarNet:
    model_args = dict(base_dim=24, depths=[1, 2, 4, 2], mlp_ratio=3)
    return _create_starnet('starnet_s150', pretrained=pretrained, **dict(model_args, **kwargs))
