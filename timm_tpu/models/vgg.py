"""VGG (reference: timm/models/vgg.py:1-426), TPU-native NHWC."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union, cast

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, ClassifierHead, create_conv2d, get_act_fn
from ..layers.drop import Dropout
from ..layers.weight_init import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .resnet import max_pool2d

__all__ = ['VGG']

_cfgs: Dict[str, List[Any]] = {
    'vgg11': [64, 'M', 128, 'M', 256, 256, 'M', 512, 512, 'M', 512, 512, 'M'],
    'vgg13': [64, 64, 'M', 128, 128, 'M', 256, 256, 'M', 512, 512, 'M', 512, 512, 'M'],
    'vgg16': [64, 64, 'M', 128, 128, 'M', 256, 256, 256, 'M', 512, 512, 512, 'M', 512, 512, 512, 'M'],
    'vgg19': [64, 64, 'M', 128, 128, 'M', 256, 256, 256, 256, 'M', 512, 512, 512, 512, 'M', 512, 512, 512, 512, 'M'],
}


class ConvMlpHead(nnx.Module):
    """VGG's fc6/fc7 conv head (reference vgg.py ConvMlp)."""

    def __init__(self, in_features=512, out_features=4096, kernel_size=7, mlp_ratio=1.0,
                 drop_rate: float = 0.2, act_layer='relu', *, dtype=None, param_dtype=jnp.float32, rngs):
        self.input_kernel_size = kernel_size
        mid_features = int(out_features * mlp_ratio)
        self.fc1 = create_conv2d(in_features, mid_features, kernel_size, bias=True, padding='valid',
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act1 = get_act_fn(act_layer)
        self.drop = Dropout(drop_rate, rngs=rngs)
        self.fc2 = create_conv2d(mid_features, out_features, 1, bias=True,
                                 dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act2 = get_act_fn(act_layer)

    def __call__(self, x):
        x = self.act1(self.fc1(x))
        x = self.drop(x)
        return self.act2(self.fc2(x))


class VGG(nnx.Module):
    def __init__(
            self,
            cfg: List[Any],
            num_classes: int = 1000,
            in_chans: int = 3,
            output_stride: int = 32,
            mlp_ratio: float = 1.0,
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Optional[Callable] = None,
            global_pool: str = 'avg',
            drop_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.use_norm = norm_layer is not None
        self.feature_info = []

        prev_chs = in_chans
        net_stride = 1
        layers = []  # list of ('conv', conv, norm|None) / ('pool',)
        convs = []
        norms = []
        plan = []
        for v in cfg:
            if v == 'M':
                plan.append(('pool', None))
                net_stride *= 2
            else:
                v = cast(int, v)
                conv = create_conv2d(prev_chs, v, 3, padding='same', bias=True,
                                     dtype=dtype, param_dtype=param_dtype, rngs=rngs)
                norm = norm_layer(v, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
                    if self.use_norm else None
                convs.append(conv)
                norms.append(norm)
                plan.append(('conv', len(convs) - 1))
                prev_chs = v
        # feature info per pre-pool stage
        stage_chs = [c for c in cfg if c != 'M']
        red = 1
        for v in cfg:
            if v == 'M':
                red *= 2
        self.plan = plan
        self.convs = nnx.List(convs)
        self.norms = nnx.List([n for n in norms if n is not None]) if self.use_norm else None
        self._norm_map = {i: j for j, i in enumerate([k for k, n in enumerate(norms) if n is not None])}
        self.act = get_act_fn(act_layer)

        # feature_info: record after each pool
        chs = in_chans
        red = 1
        for v in cfg:
            if v == 'M':
                red *= 2
                self.feature_info.append(dict(num_chs=chs, reduction=red, module=f'features.{len(self.feature_info)}'))
            else:
                chs = cast(int, v)

        self.num_features = prev_chs
        self.head_hidden_size = 4096
        self.pre_logits = ConvMlpHead(
            prev_chs, 4096, 7, mlp_ratio=mlp_ratio, drop_rate=drop_rate, act_layer=act_layer,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.head = ClassifierHead(
            4096, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False

    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^convs\.0', blocks=r'^convs\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    def forward_features(self, x):
        for kind, idx in self.plan:
            if kind == 'pool':
                x = max_pool2d(x, 2, 2)
            else:
                x = self.convs[idx](x)
                if self.use_norm:
                    x = self.norms[self._norm_map[idx]](x)
                else:
                    x = self.act(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        # pad spatial to the fc6 kernel if needed (small inputs)
        k = self.pre_logits.input_kernel_size
        if x.shape[1] < k or x.shape[2] < k:
            pad_h = max(0, k - x.shape[1])
            pad_w = max(0, k - x.shape[2])
            x = jnp.pad(x, ((0, 0), (0, pad_h), (0, 0 if not pad_w else pad_w), (0, 0)))
        x = self.pre_logits(x)
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        num_stages = len(self.feature_info)
        take_indices, max_index = feature_take_indices(num_stages, indices)
        intermediates = []
        stage = 0
        for kind, idx in self.plan:
            if kind == 'pool':
                if stage in take_indices:
                    intermediates.append(x)
                x = max_pool2d(x, 2, 2)
                stage += 1
                if stop_early and stage > max_index:
                    break
            else:
                x = self.convs[idx](x)
                if self.use_norm:
                    x = self.norms[self._norm_map[idx]](x)
                else:
                    x = self.act(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, _ = feature_take_indices(len(self.feature_info), indices)
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bilinear',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'convs.0', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'vgg11.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg13.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg16.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg19.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg11_bn.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg13_bn.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg16_bn.tv_in1k': _cfg(hf_hub_id='timm/'),
    'vgg19_bn.tv_in1k': _cfg(hf_hub_id='timm/'),
})


def checkpoint_filter_fn(state_dict, model):
    """Map reference vgg Sequential feature indices → convs/norms lists
    (conv order == appearance order of 4D weights)."""
    import re
    from ._torch_convert import convert_torch_state_dict
    import numpy as np
    feat_idx = sorted({int(m.group(1)) for k in state_dict
                       for m in [re.match(r'^features\.(\d+)\.weight$', k)] if m
                       and np.asarray(state_dict[k]).ndim == 4})
    conv_map = {idx: i for i, idx in enumerate(feat_idx)}
    bn_idx = sorted({int(m.group(1)) for k in state_dict
                     for m in [re.match(r'^features\.(\d+)\.weight$', k)] if m
                     and np.asarray(state_dict[k]).ndim == 1})
    bn_map = {idx: i for i, idx in enumerate(bn_idx)}
    out = {}
    for k, v in state_dict.items():
        m = re.match(r'^features\.(\d+)\.(.*)$', k)
        if m:
            idx, rest = int(m.group(1)), m.group(2)
            if idx in conv_map and (np.asarray(v).ndim == 4 or rest == 'bias' and idx in conv_map):
                k = f'convs.{conv_map[idx]}.{rest}'
            if idx in bn_map and np.asarray(v).ndim == 1 and idx not in conv_map:
                k = f'norms.{bn_map[idx]}.{rest}'
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_vgg(variant: str, pretrained: bool = False, **kwargs) -> VGG:
    arch = variant.split('_')[0]
    if variant.endswith('_bn'):
        kwargs.setdefault('norm_layer', BatchNormAct2d)
    return build_model_with_cfg(
        VGG, variant, pretrained,
        model_cfg=_cfgs[arch],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **kwargs,
    )


@register_model
def vgg11(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg11', pretrained, **kwargs)


@register_model
def vgg13(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg13', pretrained, **kwargs)


@register_model
def vgg16(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg16', pretrained, **kwargs)


@register_model
def vgg19(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg19', pretrained, **kwargs)


@register_model
def vgg11_bn(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg11_bn', pretrained, **kwargs)


@register_model
def vgg13_bn(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg13_bn', pretrained, **kwargs)


@register_model
def vgg16_bn(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg16_bn', pretrained, **kwargs)


@register_model
def vgg19_bn(pretrained=False, **kwargs) -> VGG:
    return _create_vgg('vgg19_bn', pretrained, **kwargs)
