"""MetaFormer baselines (PoolFormer v1/v2, ConvFormer, CAFormer), TPU-native
(reference: timm/models/metaformer.py:1-1370; Yu et al. 2022).

One trunk parameterized by the token mixer per stage: 3x3-avg-pool delta
(PoolFormer), separable inverted conv (ConvFormer), or vanilla attention
(CAFormer upper stages). NHWC collapses the reference's NCHW/NLC dual code
paths — attention stages just flatten the spatial axes.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Dropout, DropPath, GroupNorm1, LayerNorm, LayerNorm2d, Pool2d,
    SelectAdaptivePool2d, calculate_drop_path_rates, get_act_fn, to_ntuple,
    trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, resolve_stage_scan, scan_stage_stack,
    warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['MetaFormer']


class GroupNorm1NoBias(nnx.GroupNorm):
    def __init__(self, num_channels, eps: float = 1e-6, *, dtype=None,
                 param_dtype=jnp.float32, rngs: nnx.Rngs):
        super().__init__(num_channels, num_groups=1, epsilon=eps, use_bias=False,
                         use_scale=True, dtype=dtype, param_dtype=param_dtype, rngs=rngs)


class LayerNormNoBias(nnx.LayerNorm):
    def __init__(self, num_channels, eps: float = 1e-6, *, dtype=None,
                 param_dtype=jnp.float32, rngs: nnx.Rngs):
        super().__init__(num_channels, epsilon=eps, use_bias=False, use_scale=True,
                         dtype=dtype, param_dtype=param_dtype, rngs=rngs)


LayerNorm2dNoBias = LayerNormNoBias  # NHWC: per-position channel norm


class StarReLU(nnx.Module):
    """s * relu(x)^2 + b with learnable scalars (reference metaformer.py:161)."""

    def __init__(self, scale_value=1.0, bias_value=0.0, *, param_dtype=jnp.float32, rngs=None):
        self.scale = nnx.Param(jnp.full((1,), scale_value, param_dtype))
        self.bias = nnx.Param(jnp.full((1,), bias_value, param_dtype))

    def __call__(self, x):
        r = jax.nn.relu(x)
        return self.scale[...].astype(x.dtype) * r * r + self.bias[...].astype(x.dtype)


class _ActModule(nnx.Module):
    """Wraps a parameter-free activation as a module for name symmetry."""

    def __init__(self, act, *, rngs=None):
        self._fn = get_act_fn(act)

    def __call__(self, x):
        return self._fn(x)


def _make_act(act, rngs):
    if act == 'starrelu':
        return StarReLU(rngs=rngs)
    return _ActModule(act)


class Scale(nnx.Module):
    """Per-channel learned scale (reference metaformer.py:125)."""

    def __init__(self, dim, init_value=1.0, *, param_dtype=jnp.float32, rngs=None):
        self.scale = nnx.Param(jnp.full((dim,), init_value, param_dtype))

    def __call__(self, x):
        return x * self.scale[...].astype(x.dtype)


class Pooling(nnx.Module):
    """avgpool(x) - x token mixer (reference metaformer.py:316); avg pool is
    3x3 s1 p1 with count_include_pad=False (Pool2d's semantics)."""

    def __init__(self, dim=None, pool_size=3, proj_drop=0.0, *, dtype=None,
                 param_dtype=jnp.float32, rngs=None):
        self.pool = Pool2d('avg', pool_size, 1, pool_size // 2)

    def __call__(self, x):
        return self.pool(x) - x


class SepConv(nnx.Module):
    """Inverted separable conv mixer (reference metaformer.py:272)."""

    def __init__(self, dim, expansion_ratio=2.0, act1_layer='starrelu', act2_layer=None,
                 bias=False, kernel_size=7, padding=3, proj_drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        mid = int(expansion_ratio * dim)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.pwconv1 = nnx.Linear(dim, mid, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                                  bias_init=zeros_, **kw)
        self.act1 = _make_act(act1_layer, rngs)
        self.dwconv = nnx.Conv(mid, mid, kernel_size=(kernel_size, kernel_size),
                               padding=[(padding, padding), (padding, padding)],
                               feature_group_count=mid, use_bias=bias, **kw)
        self.act2 = _make_act(act2_layer, rngs) if act2_layer else None
        self.pwconv2 = nnx.Linear(mid, dim, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                                  bias_init=zeros_, **kw)

    def __call__(self, x):
        x = self.act1(self.pwconv1(x))
        x = self.dwconv(x)
        if self.act2 is not None:
            x = self.act2(x)
        return self.pwconv2(x)


class MetaAttention(nnx.Module):
    """Plain MHSA over flattened spatial tokens (reference metaformer.py:188)."""

    def __init__(self, dim, head_dim=32, num_heads=None, qkv_bias=False,
                 attn_drop=0.0, proj_drop=0.0, proj_bias=False,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.head_dim = head_dim
        self.scale = head_dim ** -0.5
        self.num_heads = num_heads if num_heads else max(dim // head_dim, 1)
        self.attention_dim = self.num_heads * head_dim
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.qkv = nnx.Linear(dim, self.attention_dim * 3, use_bias=qkv_bias,
                              kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, **kw)
        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = nnx.Linear(self.attention_dim, dim, use_bias=proj_bias,
                               kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, **kw)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x):
        B, H, W, C = x.shape
        N = H * W
        qkv = self.qkv(x).reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0] * self.scale, qkv[1], qkv[2]
        attn = jnp.einsum('bhnd,bhmd->bhnm', q, k)
        attn = jax.nn.softmax(attn, axis=-1)
        attn = self.attn_drop(attn)
        y = jnp.einsum('bhnm,bhmd->bhnd', attn, v)
        # attention_dim may differ from dim (dim not divisible by head_dim);
        # proj maps it back
        y = y.transpose(0, 2, 1, 3).reshape(B, H, W, self.attention_dim)
        y = self.proj(y)
        return self.proj_drop(y)


_MIXERS = {'pooling': Pooling, 'sepconv': SepConv, 'attention': MetaAttention}


class MetaMlp(nnx.Module):
    """MLP with a module act (StarReLU carries params) — names fc1/act/fc2
    match the reference Mlp layout."""

    def __init__(self, dim, hidden, act='starrelu', bias=False, drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc1 = nnx.Linear(dim, hidden, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                              bias_init=zeros_, **kw)
        self.act = _make_act(act, rngs)
        self.drop1 = Dropout(drop, rngs=rngs)
        self.fc2 = nnx.Linear(hidden, dim, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                              bias_init=zeros_, **kw)
        self.drop2 = Dropout(drop, rngs=rngs)

    def __call__(self, x):
        x = self.drop1(self.act(self.fc1(x)))
        return self.drop2(self.fc2(x))


class MetaFormerBlock(nnx.Module):
    """(reference metaformer.py:364-423)."""

    def __init__(self, dim, token_mixer='pooling', mlp_act='starrelu', mlp_bias=False,
                 norm_layer: Callable = LayerNorm2d, proj_drop=0.0, drop_path=0.0,
                 layer_scale_init_value=None, res_scale_init_value=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.token_mixer = _MIXERS[token_mixer](dim=dim, proj_drop=proj_drop, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.layer_scale1 = Scale(dim, layer_scale_init_value, param_dtype=param_dtype) \
            if layer_scale_init_value is not None else None
        self.res_scale1 = Scale(dim, res_scale_init_value, param_dtype=param_dtype) \
            if res_scale_init_value is not None else None
        self.norm2 = norm_layer(dim, rngs=rngs)
        self.mlp = MetaMlp(dim, 4 * dim, act=mlp_act, bias=mlp_bias, drop=proj_drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)
        self.layer_scale2 = Scale(dim, layer_scale_init_value, param_dtype=param_dtype) \
            if layer_scale_init_value is not None else None
        self.res_scale2 = Scale(dim, res_scale_init_value, param_dtype=param_dtype) \
            if res_scale_init_value is not None else None

    def __call__(self, x):
        y = self.drop_path1(self.token_mixer(self.norm1(x)))
        if self.layer_scale1 is not None:
            y = self.layer_scale1(y)
        x = (self.res_scale1(x) if self.res_scale1 is not None else x) + y
        y = self.drop_path2(self.mlp(self.norm2(x)))
        if self.layer_scale2 is not None:
            y = self.layer_scale2(y)
        x = (self.res_scale2(x) if self.res_scale2 is not None else x) + y
        return x


class Downsampling(nnx.Module):
    def __init__(self, in_chs, out_chs, kernel_size, stride=1, padding=0,
                 norm_layer=None, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm = norm_layer(in_chs, rngs=rngs) if norm_layer else None
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(kernel_size, kernel_size), strides=stride,
            padding=[(padding, padding), (padding, padding)],
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        if self.norm is not None:
            x = self.norm(x)
        return self.conv(x)


class MetaFormerStage(nnx.Module):
    def __init__(self, in_chs, out_chs, depth=2, token_mixer='pooling', mlp_act='starrelu',
                 mlp_bias=False, downsample_norm=None, norm_layer: Callable = LayerNorm2d,
                 proj_drop=0.0, dp_rates=(0.0, 0.0), layer_scale_init_value=None,
                 res_scale_init_value=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.grad_checkpointing = False
        self.stage_scan = False
        self.downsample = None if in_chs == out_chs else Downsampling(
            in_chs, out_chs, kernel_size=3, stride=2, padding=1, norm_layer=downsample_norm, **kw)
        self.blocks = nnx.List([
            MetaFormerBlock(
                dim=out_chs, token_mixer=token_mixer, mlp_act=mlp_act, mlp_bias=mlp_bias,
                norm_layer=norm_layer, proj_drop=proj_drop, drop_path=dp_rates[i],
                layer_scale_init_value=layer_scale_init_value,
                res_scale_init_value=res_scale_init_value, **kw)
            for i in range(depth)
        ])

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        if self.stage_scan:
            try:
                return scan_stage_stack(self.blocks, x, remat=self.grad_checkpointing)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e, what='stage_scan')
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class _Stem(nnx.Module):
    def __init__(self, in_chs, out_chs, norm_layer=None, *, dtype=None,
                 param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(7, 7), strides=4, padding=[(2, 2), (2, 2)],
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(out_chs, rngs=rngs) if norm_layer else None

    def __call__(self, x):
        x = self.conv(x)
        return self.norm(x) if self.norm is not None else x


class MlpHead(nnx.Module):
    """fc1 → squared relu → norm → fc2 (reference metaformer.py:330)."""

    def __init__(self, dim, num_classes=1000, mlp_ratio=4.0, drop_rate=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        hidden = int(mlp_ratio * dim)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.fc1 = nnx.Linear(dim, hidden, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, **kw)
        self.norm = LayerNorm(hidden, rngs=rngs)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.fc2 = nnx.Linear(hidden, num_classes, kernel_init=trunc_normal_(std=0.02),
                              bias_init=zeros_, **kw)

    def __call__(self, x):
        r = jax.nn.relu(self.fc1(x))
        x = self.norm(r * r)
        return self.fc2(self.head_drop(x))


class _Head(nnx.Module):
    def __init__(self, num_features, num_classes, global_pool='avg', drop_rate=0.0,
                 use_mlp_head=True, output_norm: Callable = LayerNorm2d,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.norm = output_norm(num_features, rngs=rngs)
        self.drop = Dropout(drop_rate if use_mlp_head else 0.0, rngs=rngs)
        if num_classes > 0:
            if use_mlp_head:
                self.fc = MlpHead(num_features, num_classes, drop_rate=drop_rate,
                                  dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            else:
                self.fc = nnx.Linear(
                    num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
                    bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.fc = None

    def __call__(self, x, pre_logits: bool = False):
        x = self.global_pool(x[:, None, None, :] if x.ndim == 2 else x)
        x = self.norm(x)
        x = self.drop(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)


class MetaFormer(nnx.Module):
    """MetaFormer with the reference's model contract
    (reference metaformer.py:499-744)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            depths: Tuple[int, ...] = (2, 2, 6, 2),
            dims: Tuple[int, ...] = (64, 128, 320, 512),
            token_mixers: Union[str, List[str]] = 'pooling',
            mlp_act: str = 'starrelu',
            mlp_bias: bool = False,
            drop_path_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            drop_rate: float = 0.0,
            layer_scale_init_values=None,
            res_scale_init_values=(None, None, 1.0, 1.0),
            downsample_norm: Optional[Callable] = LayerNorm2dNoBias,
            norm_layers: Union[Callable, List[Callable]] = LayerNorm2dNoBias,
            output_norm: Callable = LayerNorm2d,
            use_mlp_head: bool = True,
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        self.num_features = dims[-1]
        self.head_hidden_size = dims[-1]
        self.drop_rate = drop_rate
        self.use_mlp_head = use_mlp_head
        num_stages = len(depths)
        if not isinstance(token_mixers, (list, tuple)):
            token_mixers = [token_mixers] * num_stages
        if not isinstance(norm_layers, (list, tuple)):
            norm_layers = [norm_layers] * num_stages
        if not isinstance(layer_scale_init_values, (list, tuple)):
            layer_scale_init_values = [layer_scale_init_values] * num_stages
        if not isinstance(res_scale_init_values, (list, tuple)):
            res_scale_init_values = [res_scale_init_values] * num_stages
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.stem = _Stem(in_chans, dims[0], norm_layer=downsample_norm, **kw)
        dp_rates = calculate_drop_path_rates(drop_path_rate, list(depths), stagewise=True)
        stages = []
        prev_dim = dims[0]
        self.feature_info = []
        for i in range(num_stages):
            stages.append(MetaFormerStage(
                prev_dim, dims[i], depth=depths[i], token_mixer=token_mixers[i],
                mlp_act=mlp_act, mlp_bias=mlp_bias, proj_drop=proj_drop_rate,
                dp_rates=dp_rates[i], layer_scale_init_value=layer_scale_init_values[i],
                res_scale_init_value=res_scale_init_values[i],
                downsample_norm=downsample_norm, norm_layer=norm_layers[i], **kw))
            prev_dim = dims[i]
            self.feature_info += [dict(num_chs=dims[i], reduction=2 ** (i + 2), module=f'stages.{i}')]
        self.stages = nnx.List(stages)
        self.set_stage_scan(resolve_stage_scan(stage_scan))
        self.head = _Head(
            self.num_features, num_classes, global_pool=global_pool, drop_rate=drop_rate,
            use_mlp_head=use_mlp_head, output_norm=output_norm, **kw)
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()  # reference also decays StarReLU/Scale params

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=r'^stages\.(\d+)' if coarse else r'^stages\.(\d+)\.blocks\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        for s in self.stages:
            s.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        # replace only the fc (reference keeps the trained head.norm)
        self.num_classes = num_classes
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        if global_pool is not None:
            self.head.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        if num_classes > 0:
            if self.use_mlp_head:
                self.head.fc = MlpHead(
                    self.num_features, num_classes, drop_rate=self.drop_rate,
                    dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)
            else:
                self.head.fc = nnx.Linear(
                    self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
                    bias_init=zeros_, dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)
        else:
            self.head.fc = None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self.stem(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    return convert_torch_state_dict(state_dict, model)


def _create_metaformer(variant, pretrained=False, **kwargs):
    default_out_indices = tuple(range(len(kwargs.get('depths', (2, 2, 6, 2)))))
    out_indices = kwargs.pop('out_indices', default_out_indices)
    return build_model_with_cfg(
        MetaFormer, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 1.0, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.conv', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'poolformer_s12.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'poolformer_s24.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'poolformer_s36.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.9),
    'poolformer_m36.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95),
    'poolformer_m48.sail_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95),
    'poolformerv2_s12.sail_in1k': _cfg(hf_hub_id='timm/'),
    'poolformerv2_s24.sail_in1k': _cfg(hf_hub_id='timm/'),
    'poolformerv2_s36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'poolformerv2_m36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'poolformerv2_m48.sail_in1k': _cfg(hf_hub_id='timm/'),
    'convformer_s18.sail_in1k': _cfg(hf_hub_id='timm/'),
    'convformer_s36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'convformer_m36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'convformer_b36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'caformer_s18.sail_in1k': _cfg(hf_hub_id='timm/'),
    'caformer_s36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'caformer_m36.sail_in1k': _cfg(hf_hub_id='timm/'),
    'caformer_b36.sail_in1k': _cfg(hf_hub_id='timm/'),
})


def _poolformer_v1_args(**kwargs):
    return dict(
        downsample_norm=None, mlp_act='gelu', mlp_bias=True, norm_layers=GroupNorm1,
        layer_scale_init_values=1e-5, res_scale_init_values=None, use_mlp_head=False,
        **kwargs)


@register_model
def poolformer_s12(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = _poolformer_v1_args(depths=(2, 2, 6, 2), dims=(64, 128, 320, 512), **kwargs)
    return _create_metaformer('poolformer_s12', pretrained=pretrained, **model_kwargs)


@register_model
def poolformer_s24(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = _poolformer_v1_args(depths=(4, 4, 12, 4), dims=(64, 128, 320, 512), **kwargs)
    return _create_metaformer('poolformer_s24', pretrained=pretrained, **model_kwargs)


@register_model
def poolformer_s36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = _poolformer_v1_args(
        depths=(6, 6, 18, 6), dims=(64, 128, 320, 512), layer_scale_init_values=1e-6, **kwargs)
    return _create_metaformer('poolformer_s36', pretrained=pretrained, **model_kwargs)


@register_model
def poolformer_m36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = _poolformer_v1_args(
        depths=(6, 6, 18, 6), dims=(96, 192, 384, 768), layer_scale_init_values=1e-6, **kwargs)
    return _create_metaformer('poolformer_m36', pretrained=pretrained, **model_kwargs)


@register_model
def poolformer_m48(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = _poolformer_v1_args(
        depths=(8, 8, 24, 8), dims=(96, 192, 384, 768), layer_scale_init_values=1e-6, **kwargs)
    return _create_metaformer('poolformer_m48', pretrained=pretrained, **model_kwargs)


@register_model
def poolformerv2_s12(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(2, 2, 6, 2), dims=(64, 128, 320, 512),
                        norm_layers=GroupNorm1NoBias, use_mlp_head=False, **kwargs)
    return _create_metaformer('poolformerv2_s12', pretrained=pretrained, **model_kwargs)


@register_model
def poolformerv2_s24(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(4, 4, 12, 4), dims=(64, 128, 320, 512),
                        norm_layers=GroupNorm1NoBias, use_mlp_head=False, **kwargs)
    return _create_metaformer('poolformerv2_s24', pretrained=pretrained, **model_kwargs)


@register_model
def poolformerv2_s36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(6, 6, 18, 6), dims=(64, 128, 320, 512),
                        norm_layers=GroupNorm1NoBias, use_mlp_head=False, **kwargs)
    return _create_metaformer('poolformerv2_s36', pretrained=pretrained, **model_kwargs)


@register_model
def poolformerv2_m36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(6, 6, 18, 6), dims=(96, 192, 384, 768),
                        norm_layers=GroupNorm1NoBias, use_mlp_head=False, **kwargs)
    return _create_metaformer('poolformerv2_m36', pretrained=pretrained, **model_kwargs)


@register_model
def poolformerv2_m48(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(8, 8, 24, 8), dims=(96, 192, 384, 768),
                        norm_layers=GroupNorm1NoBias, use_mlp_head=False, **kwargs)
    return _create_metaformer('poolformerv2_m48', pretrained=pretrained, **model_kwargs)


@register_model
def convformer_s18(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(3, 3, 9, 3), dims=(64, 128, 320, 512),
                        token_mixers='sepconv', norm_layers=LayerNorm2dNoBias, **kwargs)
    return _create_metaformer('convformer_s18', pretrained=pretrained, **model_kwargs)


@register_model
def convformer_s36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(3, 12, 18, 3), dims=(64, 128, 320, 512),
                        token_mixers='sepconv', norm_layers=LayerNorm2dNoBias, **kwargs)
    return _create_metaformer('convformer_s36', pretrained=pretrained, **model_kwargs)


@register_model
def convformer_m36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(3, 12, 18, 3), dims=(96, 192, 384, 576),
                        token_mixers='sepconv', norm_layers=LayerNorm2dNoBias, **kwargs)
    return _create_metaformer('convformer_m36', pretrained=pretrained, **model_kwargs)


@register_model
def convformer_b36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(depths=(3, 12, 18, 3), dims=(128, 256, 512, 768),
                        token_mixers='sepconv', norm_layers=LayerNorm2dNoBias, **kwargs)
    return _create_metaformer('convformer_b36', pretrained=pretrained, **model_kwargs)


@register_model
def caformer_s18(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(
        depths=(3, 3, 9, 3), dims=(64, 128, 320, 512),
        token_mixers=['sepconv', 'sepconv', 'attention', 'attention'],
        norm_layers=[LayerNorm2dNoBias] * 2 + [LayerNormNoBias] * 2, **kwargs)
    return _create_metaformer('caformer_s18', pretrained=pretrained, **model_kwargs)


@register_model
def caformer_s36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(
        depths=(3, 12, 18, 3), dims=(64, 128, 320, 512),
        token_mixers=['sepconv', 'sepconv', 'attention', 'attention'],
        norm_layers=[LayerNorm2dNoBias] * 2 + [LayerNormNoBias] * 2, **kwargs)
    return _create_metaformer('caformer_s36', pretrained=pretrained, **model_kwargs)


@register_model
def caformer_m36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(
        depths=(3, 12, 18, 3), dims=(96, 192, 384, 576),
        token_mixers=['sepconv', 'sepconv', 'attention', 'attention'],
        norm_layers=[LayerNorm2dNoBias] * 2 + [LayerNormNoBias] * 2, **kwargs)
    return _create_metaformer('caformer_m36', pretrained=pretrained, **model_kwargs)


@register_model
def caformer_b36(pretrained=False, **kwargs) -> MetaFormer:
    model_kwargs = dict(
        depths=(3, 12, 18, 3), dims=(128, 256, 512, 768),
        token_mixers=['sepconv', 'sepconv', 'attention', 'attention'],
        norm_layers=[LayerNorm2dNoBias] * 2 + [LayerNormNoBias] * 2, **kwargs)
    return _create_metaformer('caformer_b36', pretrained=pretrained, **model_kwargs)
