"""Hybrid Vision Transformer (CNN backbone + ViT) — NHWC / nnx.

Re-implements reference timm/models/vision_transformer_hybrid.py:1-520:
ResNetV2 (BiT) stems/stages feeding a VisionTransformer through HybridEmbed,
plus the custom resnet26d/50d hybrids and the MobileCLIP-B ConvStem variant.

TPU notes: backbones are the NHWC ResNetV2/ResNet from this package with
TF-SAME ('same') padded weight-standardized convs (the original R+ViT weights
were trained in JAX with SAME padding, so this is the native convention
round-tripping home); the ViT side is unchanged — one extra conv trunk in
front of the same fused attention blocks.
"""
from functools import partial
from typing import Any, Dict, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from timm_tpu.layers import ConvNormAct, HybridEmbed, StdConv2d, to_ntuple
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .resnet import resnet26d, resnet50d
from .resnetv2 import ResNetV2, Stem as ResNetV2Stem
from .vision_transformer import VisionTransformer
from .vision_transformer import checkpoint_filter_fn as _vit_checkpoint_filter_fn

__all__ = []


class ConvStem(nnx.Module):
    """Simple tiered conv stem (reference vision_transformer_hybrid.py:33-74).

    A sequence of ConvNormAct blocks; the last one is conv-only (bias, no
    norm/act) so it acts as the patch projection when HybridEmbed runs with
    ``proj=False``.
    """

    def __init__(
            self,
            in_chans: int = 3,
            depth: int = 3,
            channels: Union[int, Tuple[int, ...]] = 64,
            kernel_size: Union[int, Tuple[int, ...]] = 3,
            stride: Union[int, Tuple[int, ...]] = (2, 2, 2),
            padding: Union[str, int, Tuple] = '',
            norm_layer=None,
            act_layer='relu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        if isinstance(channels, int):
            channels = tuple([channels // 2 ** i for i in range(depth)][::-1])
        kernel_size = to_ntuple(depth)(kernel_size)
        padding = to_ntuple(depth)(padding)
        assert depth == len(stride) == len(kernel_size) == len(channels)

        blocks = []
        in_chs = in_chans
        for i in range(len(channels)):
            last_conv = i == len(channels) - 1
            blocks.append(ConvNormAct(
                in_chs, channels[i], kernel_size=kernel_size[i], stride=stride[i],
                padding=padding[i], bias=last_conv,
                apply_norm=not last_conv, apply_act=not last_conv,
                norm_layer=norm_layer, act_layer=act_layer,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs))
            in_chs = channels[i]
        self.blocks = nnx.List(blocks)
        self.num_features = channels[-1]

    def __call__(self, x):
        for b in self.blocks:
            x = b(x)
        return x


def _backbone_rngs(kwargs):
    """Backbone rngs matching the builder's seed derivation (_builder.py:218-224),
    so `seed=N` varies the CNN half too, not just the ViT."""
    rngs = kwargs.get('rngs')
    if rngs is None:
        # offset from the ViT's (seed, seed+1) streams so same-shaped params in
        # the two halves never share an init key
        seed = kwargs.get('seed', 0)
        rngs = nnx.Rngs(params=seed + 2, dropout=seed + 3)
    return rngs


def _resnetv2(layers=(3, 4, 9), **kwargs):
    """BiT ResNetV2 backbone helper (reference vision_transformer_hybrid.py:81-104).

    The released hybrid weights use TF-SAME padding (JAX-trained), hence
    stem_type='same' and 'same'-padded StdConv2d throughout.
    """
    conv_layer = partial(StdConv2d, eps=1e-8, padding='same')
    rngs = _backbone_rngs(kwargs)
    dd = dict(dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32))
    if len(layers):
        return ResNetV2(
            layers=layers, num_classes=0, global_pool='',
            in_chans=kwargs.get('in_chans', 3),
            preact=False, stem_type='same', conv_layer=conv_layer, rngs=rngs, **dd)
    return ResNetV2Stem(
        kwargs.get('in_chans', 3), 64, stem_type='same', preact=False,
        conv_layer=conv_layer, rngs=rngs, **dd)


def checkpoint_filter_fn(state_dict, model):
    """Torch hybrid checkpoints name ConvStem children numerically
    (``patch_embed.backbone.0.conv``, nn.Sequential); our ConvStem holds them
    in ``blocks``. Remap, then defer to the standard ViT converter."""
    import re
    state_dict = {
        re.sub(r'^(patch_embed\.backbone\.)(\d+)\.', r'\1blocks.\2.', k): v
        for k, v in state_dict.items()
    }
    return _vit_checkpoint_filter_fn(state_dict, model)


def _create_vision_transformer_hybrid(variant, backbone, embed_args=None, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', 3)
    embed_args = embed_args or {}
    embed_layer = partial(HybridEmbed, backbone=backbone, **embed_args)
    kwargs.setdefault('embed_layer', embed_layer)
    kwargs.setdefault('patch_size', 1)  # project 1x1 feature patches unless overridden
    return build_model_with_cfg(
        VisionTransformer,
        variant,
        pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': 0.9, 'interpolation': 'bicubic', 'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5), 'std': (0.5, 0.5, 0.5),
        'first_conv': 'patch_embed.backbone.stem.conv', 'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'vit_tiny_r_s16_p8_224.augreg_in21k_ft_in1k': _cfg(first_conv='patch_embed.backbone.conv'),
    'vit_tiny_r_s16_p8_384.augreg_in21k_ft_in1k': _cfg(
        first_conv='patch_embed.backbone.conv', input_size=(3, 384, 384), crop_pct=1.0),
    'vit_small_r26_s32_224.augreg_in21k_ft_in1k': _cfg(),
    'vit_small_r26_s32_384.augreg_in21k_ft_in1k': _cfg(input_size=(3, 384, 384), crop_pct=1.0),
    'vit_base_r26_s32_224.untrained': _cfg(),
    'vit_base_r50_s16_224.orig_in21k': _cfg(num_classes=0, crop_pct=0.9),
    'vit_base_r50_s16_384.orig_in21k_ft_in1k': _cfg(input_size=(3, 384, 384), crop_pct=1.0),
    'vit_large_r50_s32_224.augreg_in21k_ft_in1k': _cfg(),
    'vit_large_r50_s32_384.augreg_in21k_ft_in1k': _cfg(input_size=(3, 384, 384), crop_pct=1.0),
    'vit_small_resnet26d_224.untrained': _cfg(
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD, first_conv='patch_embed.backbone.model.conv1.0'),
    'vit_small_resnet50d_s16_224.untrained': _cfg(
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD, first_conv='patch_embed.backbone.model.conv1.0'),
    'vit_base_resnet26d_224.untrained': _cfg(
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD, first_conv='patch_embed.backbone.model.conv1.0'),
    'vit_base_resnet50d_224.untrained': _cfg(
        mean=IMAGENET_DEFAULT_MEAN, std=IMAGENET_DEFAULT_STD, first_conv='patch_embed.backbone.model.conv1.0'),
    'vit_base_mci_224.apple_mclip': _cfg(
        num_classes=512, mean=(0., 0., 0.), std=(1., 1., 1.),
        first_conv='patch_embed.backbone.blocks.0.conv'),
})


@register_model
def vit_tiny_r_s16_p8_224(pretrained=False, **kwargs) -> VisionTransformer:
    """R+ViT-Ti/S16 w/ 8x8 patch hybrid (reference vision_transformer_hybrid.py:265-273)."""
    backbone = _resnetv2(layers=(), **kwargs)
    model_args = dict(patch_size=8, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer_hybrid(
        'vit_tiny_r_s16_p8_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_tiny_r_s16_p8_384(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2(layers=(), **kwargs)
    model_args = dict(patch_size=8, embed_dim=192, depth=12, num_heads=3)
    return _create_vision_transformer_hybrid(
        'vit_tiny_r_s16_p8_384', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_r26_s32_224(pretrained=False, **kwargs) -> VisionTransformer:
    """R26+ViT-S/S32 hybrid (reference vision_transformer_hybrid.py:287-295)."""
    backbone = _resnetv2((2, 2, 2, 2), **kwargs)
    model_args = dict(embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer_hybrid(
        'vit_small_r26_s32_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_r26_s32_384(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2((2, 2, 2, 2), **kwargs)
    model_args = dict(embed_dim=384, depth=12, num_heads=6)
    return _create_vision_transformer_hybrid(
        'vit_small_r26_s32_384', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_r26_s32_224(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2((2, 2, 2, 2), **kwargs)
    model_args = dict(embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer_hybrid(
        'vit_base_r26_s32_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_r50_s16_224(pretrained=False, **kwargs) -> VisionTransformer:
    """R50+ViT-B/S16 hybrid from the original ViT paper (vision_transformer_hybrid.py:320-328)."""
    backbone = _resnetv2((3, 4, 9), **kwargs)
    model_args = dict(embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer_hybrid(
        'vit_base_r50_s16_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_r50_s16_384(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2((3, 4, 9), **kwargs)
    model_args = dict(embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer_hybrid(
        'vit_base_r50_s16_384', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_r50_s32_224(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2((3, 4, 6, 3), **kwargs)
    model_args = dict(embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer_hybrid(
        'vit_large_r50_s32_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_r50_s32_384(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = _resnetv2((3, 4, 6, 3), **kwargs)
    model_args = dict(embed_dim=1024, depth=24, num_heads=16)
    return _create_vision_transformer_hybrid(
        'vit_large_r50_s32_384', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_resnet26d_224(pretrained=False, **kwargs) -> VisionTransformer:
    """ViT-S hybrid on ResNet26D stride-32 features (vision_transformer_hybrid.py:365-379)."""
    backbone = resnet26d(in_chans=kwargs.get('in_chans', 3), rngs=_backbone_rngs(kwargs), dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32), features_only=True, out_indices=[4])
    model_args = dict(embed_dim=768, depth=8, num_heads=8, mlp_ratio=3)
    return _create_vision_transformer_hybrid(
        'vit_small_resnet26d_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_resnet50d_s16_224(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = resnet50d(in_chans=kwargs.get('in_chans', 3), rngs=_backbone_rngs(kwargs), dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32), features_only=True, out_indices=[3])
    model_args = dict(embed_dim=768, depth=8, num_heads=8, mlp_ratio=3)
    return _create_vision_transformer_hybrid(
        'vit_small_resnet50d_s16_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_resnet26d_224(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = resnet26d(in_chans=kwargs.get('in_chans', 3), rngs=_backbone_rngs(kwargs), dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32), features_only=True, out_indices=[4])
    model_args = dict(embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer_hybrid(
        'vit_base_resnet26d_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_resnet50d_224(pretrained=False, **kwargs) -> VisionTransformer:
    backbone = resnet50d(in_chans=kwargs.get('in_chans', 3), rngs=_backbone_rngs(kwargs), dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32), features_only=True, out_indices=[4])
    model_args = dict(embed_dim=768, depth=12, num_heads=12)
    return _create_vision_transformer_hybrid(
        'vit_base_resnet50d_224', backbone=backbone, pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_mci_224(pretrained=False, **kwargs) -> VisionTransformer:
    """MobileCLIP-B ViT hybrid w/ tiered conv stem (vision_transformer_hybrid.py:433-451)."""
    backbone = ConvStem(
        channels=(768 // 4, 768 // 4, 768), stride=(4, 2, 2), kernel_size=(4, 2, 2),
        padding=0, in_chans=kwargs.get('in_chans', 3), act_layer='gelu',
        dtype=kwargs.get('dtype'), param_dtype=kwargs.get('param_dtype', jnp.float32),
        rngs=_backbone_rngs(kwargs))
    model_args = dict(embed_dim=768, depth=12, num_heads=12, no_embed_class=True)
    return _create_vision_transformer_hybrid(
        'vit_base_mci_224', backbone=backbone, embed_args=dict(proj=False),
        pretrained=pretrained, **dict(model_args, **kwargs))
