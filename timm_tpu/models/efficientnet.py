"""EfficientNet / EfficientNetV2 family, TPU-native NHWC
(reference: timm/models/efficientnet.py:1-2973).

Depthwise + SE + SiLU conv nets driven by the arch-string decoder
(_efficientnet_builder.py). NHWC depthwise convs map directly onto the TPU
conv units without the reference's channels_last workarounds.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, SelectAdaptivePool2d, create_conv2d, get_act_fn, get_norm_layer
from ..layers.drop import Dropout
from ..layers.weight_init import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._efficientnet_builder import (
    EfficientNetBuilder, decode_arch_def, resolve_act_layer, resolve_bn_args, round_channels,
)
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['EfficientNet']


class EfficientNet(nnx.Module):
    def __init__(
            self,
            block_args: List[List[Dict]],
            num_classes: int = 1000,
            num_features: int = 1280,
            in_chans: int = 3,
            stem_size: int = 32,
            stem_kernel_size: int = 3,
            fix_stem: bool = False,
            output_stride: int = 32,
            pad_type: str = '',
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            se_from_exp: bool = False,
            round_chs_fn: Callable = round_channels,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            global_pool: str = 'avg',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        self.drop_rate = drop_rate

        if not fix_stem:
            stem_size = round_chs_fn(stem_size)
        self.conv_stem = create_conv2d(
            in_chans, stem_size, stem_kernel_size, stride=2, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(stem_size, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        builder = EfficientNetBuilder(
            output_stride=output_stride,
            pad_type=pad_type,
            round_chs_fn=round_chs_fn,
            se_from_exp=se_from_exp,
            act_layer=act_layer,
            norm_layer=norm_layer,
            drop_path_rate=drop_path_rate,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.blocks = nnx.List(builder(stem_size, block_args))
        self.feature_info = builder.features
        head_chs = builder.in_chs

        # head
        self.num_features = num_features
        self.conv_head = create_conv2d(
            head_chs, num_features, 1, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn2 = norm_layer(num_features, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.head_hidden_size = num_features
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.classifier = nnx.Linear(
            num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self.grad_checkpointing = False
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv_stem|bn1',
            blocks=[
                (r'^blocks\.(\d+)' if coarse else r'^blocks\.(\d+)\.(\d+)', None),
                (r'conv_head|bn2', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.bn1(self.conv_stem(x))
        for stage in self.blocks:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        x = self.bn2(self.conv_head(x))
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x
        return self.classifier(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        x = self.bn1(self.conv_stem(x))
        intermediates = []
        stages = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, stage in enumerate(stages):
            for b in stage:
                x = b(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        x = self.bn2(self.conv_head(x))
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _gen_efficientnet(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """EfficientNet B0-B7 generator (reference efficientnet.py _gen_efficientnet)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_se0.25'],
        ['ir_r2_k3_s2_e6_c24_se0.25'],
        ['ir_r2_k5_s2_e6_c40_se0.25'],
        ['ir_r3_k3_s2_e6_c80_se0.25'],
        ['ir_r3_k5_s1_e6_c112_se0.25'],
        ['ir_r4_k5_s2_e6_c192_se0.25'],
        ['ir_r1_k3_s1_e6_c320_se0.25'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return build_model_with_cfg(
        EfficientNet, variant, pretrained,
        pretrained_filter_fn=_filter_fn,
        feature_cfg=dict(out_indices=(1, 2, 3, 4, 5)),
        **model_kwargs,
    )


def _gen_efficientnetv2_s(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """EfficientNet-V2 small (reference efficientnet.py _gen_efficientnetv2_s)."""
    arch_def = [
        ['cn_r2_k3_s1_e1_c24_skip'],
        ['er_r4_k3_s2_e4_c48'],
        ['er_r4_k3_s2_e4_c64'],
        ['ir_r6_k3_s2_e4_c128_se0.25'],
        ['ir_r9_k3_s1_e6_c160_se0.25'],
        ['ir_r15_k3_s2_e6_c256_se0.25'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(1280),
        stem_size=24,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return build_model_with_cfg(
        EfficientNet, variant, pretrained,
        pretrained_filter_fn=_filter_fn,
        feature_cfg=dict(out_indices=(1, 2, 3, 4, 5)),
        **model_kwargs,
    )


def _gen_efficientnetv2_m(variant, pretrained=False, **kwargs):
    arch_def = [
        ['cn_r3_k3_s1_e1_c24_skip'],
        ['er_r5_k3_s2_e4_c48'],
        ['er_r5_k3_s2_e4_c80'],
        ['ir_r7_k3_s2_e4_c160_se0.25'],
        ['ir_r14_k3_s1_e6_c176_se0.25'],
        ['ir_r18_k3_s2_e6_c304_se0.25'],
        ['ir_r5_k3_s1_e6_c512_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1280,
        stem_size=24,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return build_model_with_cfg(
        EfficientNet, variant, pretrained,
        pretrained_filter_fn=_filter_fn,
        feature_cfg=dict(out_indices=(1, 2, 3, 4, 5)),
        **model_kwargs,
    )


def _filter_fn(state_dict, model):
    """Reference SE layers name their convs conv_reduce/conv_expand."""
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = k.replace('.se.conv_reduce.', '.se.fc1.').replace('.se.conv_expand.', '.se.fc2.')
        out[k] = v
    return convert_torch_state_dict(out, model)


checkpoint_filter_fn = _filter_fn


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem',
        'classifier': 'classifier',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'efficientnet_b0.ra_in1k': _cfg(hf_hub_id='timm/'),
    'efficientnet_b1.ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), crop_pct=0.882),
    'efficientnet_b2.ra_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.89),
    'efficientnet_b3.ra2_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 288, 288), crop_pct=0.904),
    'efficientnetv2_s.in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 300, 300), test_input_size=(3, 384, 384), crop_pct=1.0),
    'efficientnetv2_m.untrained': _cfg(input_size=(3, 320, 320), test_input_size=(3, 416, 416), crop_pct=1.0),
    'tf_efficientnetv2_s.in1k': _cfg(
        hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5),
        input_size=(3, 300, 300), test_input_size=(3, 384, 384), crop_pct=1.0),
    'test_efficientnet.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
})


@register_model
def efficientnet_b0(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet('efficientnet_b0', 1.0, 1.0, pretrained, **kwargs)


@register_model
def efficientnet_b1(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet('efficientnet_b1', 1.0, 1.1, pretrained, **kwargs)


@register_model
def efficientnet_b2(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet('efficientnet_b2', 1.1, 1.2, pretrained, **kwargs)


@register_model
def efficientnet_b3(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet('efficientnet_b3', 1.2, 1.4, pretrained, **kwargs)


@register_model
def efficientnetv2_s(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_s('efficientnetv2_s', pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_m(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_m('efficientnetv2_m', pretrained=pretrained, **kwargs)


@register_model
def tf_efficientnetv2_s(pretrained=False, **kwargs) -> EfficientNet:
    """TF-origin weights variant; same arch, SAME padding is already native."""
    return _gen_efficientnetv2_s('tf_efficientnetv2_s', pretrained=pretrained, **kwargs)


@register_model
def test_efficientnet(pretrained=False, **kwargs) -> EfficientNet:
    """Tiny fixture (reference efficientnet.py:2902)."""
    arch_def = [
        ['cn_r1_k3_s1_e1_c16_skip'],
        ['er_r1_k3_s2_e4_c24'],
        ['er_r1_k3_s2_e4_c32'],
        ['ir_r1_k3_s2_e4_c48_se0.25'],
        ['ir_r1_k3_s2_e4_c64_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=256,
        stem_size=16,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return build_model_with_cfg(
        EfficientNet, 'test_efficientnet', pretrained,
        pretrained_filter_fn=_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        **model_kwargs,
    )
