"""EfficientNet / EfficientNetV2 family, TPU-native NHWC
(reference: timm/models/efficientnet.py:1-2973).

Depthwise + SE + SiLU conv nets driven by the arch-string decoder
(_efficientnet_builder.py). NHWC depthwise convs map directly onto the TPU
conv units without the reference's channels_last workarounds.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNormAct2d, EvoNorm2dS0, GroupNormAct, LayerNormAct2d, SelectAdaptivePool2d,
    SqueezeExcite, create_conv2d, get_act_fn, get_attn, get_norm_layer,
)
from ..layers.drop import Dropout
from ..layers.weight_init import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._efficientnet_builder import (
    BN_EPS_TF_DEFAULT, EfficientNetBuilder, decode_arch_def, resolve_act_layer,
    resolve_bn_args, round_channels,
)
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['EfficientNet']


class EfficientNet(nnx.Module):
    def __init__(
            self,
            block_args: List[List[Dict]],
            num_classes: int = 1000,
            num_features: int = 1280,
            in_chans: int = 3,
            stem_size: int = 32,
            stem_kernel_size: int = 3,
            fix_stem: bool = False,
            output_stride: int = 32,
            pad_type: str = '',
            act_layer: Union[str, Callable] = 'relu',
            norm_layer: Callable = BatchNormAct2d,
            aa_layer: Optional[Union[str, Callable]] = None,
            se_layer: Optional[Union[str, Callable]] = None,
            se_from_exp: bool = False,
            round_chs_fn: Callable = round_channels,
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            global_pool: str = 'avg',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_classes = num_classes
        self.drop_rate = drop_rate

        if not fix_stem:
            stem_size = round_chs_fn(stem_size)
        self.conv_stem = create_conv2d(
            in_chans, stem_size, stem_kernel_size, stride=2, padding=pad_type or None,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn1 = norm_layer(stem_size, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        builder_se = get_attn(se_layer) if isinstance(se_layer, str) else se_layer
        builder = EfficientNetBuilder(
            output_stride=output_stride,
            pad_type=pad_type,
            round_chs_fn=round_chs_fn,
            se_from_exp=se_from_exp,
            act_layer=act_layer,
            norm_layer=norm_layer,
            aa_layer=aa_layer,
            se_layer=builder_se if builder_se is not None else SqueezeExcite,
            drop_path_rate=drop_path_rate,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.blocks = nnx.List(builder(stem_size, block_args))
        self.feature_info = builder.features
        head_chs = builder.in_chs

        # head (num_features == 0 → no head conv, reference efficientnet.py:159-166)
        if num_features > 0:
            self.conv_head = create_conv2d(
                head_chs, num_features, 1, padding=pad_type or None,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            self.bn2 = norm_layer(num_features, act_layer=act_layer, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.conv_head = None
            self.bn2 = None
            num_features = head_chs
        self.num_features = num_features
        self.head_hidden_size = num_features
        self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.classifier = nnx.Linear(
            num_features, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self.grad_checkpointing = False
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^conv_stem|bn1',
            blocks=[
                (r'^blocks\.(\d+)' if coarse else r'^blocks\.(\d+)\.(\d+)', None),
                (r'conv_head|bn2', (99999,)),
            ],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.classifier

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.classifier = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.bn1(self.conv_stem(x))
        for stage in self.blocks:
            if self.grad_checkpointing:
                x = checkpoint_seq(stage, x)
            else:
                for b in stage:
                    x = b(x)
        if self.conv_head is not None:
            x = self.bn2(self.conv_head(x))
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.global_pool(x)
        x = self.head_drop(x)
        if pre_logits or self.classifier is None:
            return x
        return self.classifier(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self,
            x,
            indices: Optional[Union[int, List[int]]] = None,
            norm: bool = False,
            stop_early: bool = False,
            output_fmt: str = 'NHWC',
            intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        x = self.bn1(self.conv_stem(x))
        intermediates = []
        stages = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, stage in enumerate(stages):
            for b in stage:
                x = b(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        if self.conv_head is not None:
            x = self.bn2(self.conv_head(x))
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def _create_effnet(variant, pretrained=False, **kwargs):
    """Common builder: resolves tf-origin BN overrides (bn_eps/bn_momentum via
    resolve_bn_args) into the norm layer (reference _create_effnet +
    tf entrypoints' kwargs.setdefault('bn_eps', 1e-3))."""
    if kwargs.pop('pruned', None) and pretrained:
        # channel-pruned checkpoints need the _prune structure adaptation
        # (reference _builder.py adapt_model_from_file) which is not wired yet
        raise NotImplementedError('pruned pretrained weights not supported yet')
    bn_args = resolve_bn_args(kwargs)
    if bn_args:
        kwargs['norm_layer'] = partial(BatchNormAct2d, **bn_args)
    n_stacks = len(kwargs.get('block_args', ()))
    # standard 7-stack effnet/mnv2 shapes expose the 5 stride-level stacks like
    # the reference; shorter archs (mobilenetv1, mixnet, test fixtures) expose
    # every stack
    out_indices = (1, 2, 3, 4, 5) if n_stacks == 7 else tuple(range(n_stacks))
    return build_model_with_cfg(
        EfficientNet, variant, pretrained,
        pretrained_filter_fn=_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


def _gen_efficientnet(variant, channel_multiplier=1.0, depth_multiplier=1.0, channel_divisor=8, group_size=None, pretrained=False, **kwargs):
    """EfficientNet B0-B8/L2 generator (reference efficientnet.py:718-766)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_se0.25'],
        ['ir_r2_k3_s2_e6_c24_se0.25'],
        ['ir_r2_k5_s2_e6_c40_se0.25'],
        ['ir_r3_k3_s2_e6_c80_se0.25'],
        ['ir_r3_k5_s1_e6_c112_se0.25'],
        ['ir_r4_k5_s2_e6_c192_se0.25'],
        ['ir_r1_k3_s1_e6_c320_se0.25'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier, divisor=channel_divisor)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, group_size=group_size),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnet_edge(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """EfficientNet-EdgeTPU es/em/el (reference efficientnet.py:768-798)."""
    arch_def = [
        ['er_r1_k3_s1_e4_c24_fc24_noskip'],
        ['er_r2_k3_s2_e8_c32'],
        ['er_r4_k3_s2_e8_c48'],
        ['ir_r5_k5_s2_e8_c96'],
        ['ir_r4_k5_s1_e8_c144'],
        ['ir_r2_k5_s2_e8_c192'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'relu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnet_lite(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """EfficientNet-Lite (reference efficientnet.py:832-871)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16'],
        ['ir_r2_k3_s2_e6_c24'],
        ['ir_r2_k5_s2_e6_c40'],
        ['ir_r3_k3_s2_e6_c80'],
        ['ir_r3_k5_s1_e6_c112'],
        ['ir_r4_k5_s2_e6_c192'],
        ['ir_r1_k3_s1_e6_c320'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, fix_first_last=True),
        num_features=1280,
        stem_size=32,
        fix_stem=True,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        act_layer=resolve_act_layer(kwargs, 'relu6'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnetv2_base(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """EfficientNet-V2 base/b0-b3 (reference efficientnet.py:873-901)."""
    arch_def = [
        ['cn_r1_k3_s1_e1_c16_skip'],
        ['er_r2_k3_s2_e4_c32'],
        ['er_r2_k3_s2_e4_c48'],
        ['ir_r3_k3_s2_e4_c96_se0.25'],
        ['ir_r5_k3_s1_e6_c112_se0.25'],
        ['ir_r8_k3_s2_e6_c192_se0.25'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier, round_limit=0.0)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnetv2_s(variant, channel_multiplier=1.0, depth_multiplier=1.0, rw=False, pretrained=False, **kwargs):
    """EfficientNet-V2 small (reference efficientnet.py:903-941)."""
    arch_def = [
        ['cn_r2_k3_s1_e1_c24_skip'],
        ['er_r4_k3_s2_e4_c48'],
        ['er_r4_k3_s2_e4_c64'],
        ['ir_r6_k3_s2_e4_c128_se0.25'],
        ['ir_r9_k3_s1_e6_c160_se0.25'],
        ['ir_r15_k3_s2_e6_c256_se0.25'],
    ]
    num_features = 1280
    if rw:
        # timm's pre-release v2 small variant
        arch_def[0] = ['er_r2_k3_s1_e1_c24']
        arch_def[-1] = ['ir_r15_k3_s2_e6_c272_se0.25']
        num_features = 1792
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(num_features),
        stem_size=24,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnetv2_m(variant, pretrained=False, **kwargs):
    """EfficientNet-V2 medium (reference efficientnet.py:943-973)."""
    arch_def = [
        ['cn_r3_k3_s1_e1_c24_skip'],
        ['er_r5_k3_s2_e4_c48'],
        ['er_r5_k3_s2_e4_c80'],
        ['ir_r7_k3_s2_e4_c160_se0.25'],
        ['ir_r14_k3_s1_e6_c176_se0.25'],
        ['ir_r18_k3_s2_e6_c304_se0.25'],
        ['ir_r5_k3_s1_e6_c512_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1280,
        stem_size=24,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnetv2_l(variant, pretrained=False, **kwargs):
    """EfficientNet-V2 large (reference efficientnet.py:975-1005)."""
    arch_def = [
        ['cn_r4_k3_s1_e1_c32_skip'],
        ['er_r7_k3_s2_e4_c64'],
        ['er_r7_k3_s2_e4_c96'],
        ['ir_r10_k3_s2_e4_c192_se0.25'],
        ['ir_r19_k3_s1_e6_c224_se0.25'],
        ['ir_r25_k3_s2_e6_c384_se0.25'],
        ['ir_r7_k3_s1_e6_c640_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1280,
        stem_size=32,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnetv2_xl(variant, pretrained=False, **kwargs):
    """EfficientNet-V2 xlarge (reference efficientnet.py:1007-1037)."""
    arch_def = [
        ['cn_r4_k3_s1_e1_c32_skip'],
        ['er_r8_k3_s2_e4_c64'],
        ['er_r8_k3_s2_e4_c96'],
        ['ir_r16_k3_s2_e4_c192_se0.25'],
        ['ir_r24_k3_s1_e6_c256_se0.25'],
        ['ir_r32_k3_s2_e6_c512_se0.25'],
        ['ir_r8_k3_s1_e6_c640_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1280,
        stem_size=32,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mnasnet_a1(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """MNASNet-A1 (w/ SE) a.k.a. semnasnet (reference efficientnet.py:479-513)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_noskip'],
        ['ir_r2_k3_s2_e6_c24'],
        ['ir_r3_k5_s2_e3_c40_se0.25'],
        ['ir_r4_k3_s2_e6_c80'],
        ['ir_r2_k3_s1_e6_c112_se0.25'],
        ['ir_r3_k5_s2_e6_c160_se0.25'],
        ['ir_r1_k3_s1_e6_c320'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=32,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mnasnet_b1(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """MNASNet-B1 (reference efficientnet.py:515-549)."""
    arch_def = [
        ['ds_r1_k3_s1_c16_noskip'],
        ['ir_r3_k3_s2_e3_c24'],
        ['ir_r3_k5_s2_e3_c40'],
        ['ir_r3_k5_s2_e6_c80'],
        ['ir_r2_k3_s1_e6_c96'],
        ['ir_r4_k5_s2_e6_c192'],
        ['ir_r1_k3_s1_e6_c320_noskip'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=32,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mnasnet_small(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """MNASNet small (reference efficientnet.py:551-578)."""
    arch_def = [
        ['ds_r1_k3_s1_c8'],
        ['ir_r1_k3_s2_e3_c16'],
        ['ir_r2_k3_s2_e6_c16'],
        ['ir_r4_k5_s2_e6_c32_se0.25'],
        ['ir_r3_k3_s1_e6_c32_se0.25'],
        ['ir_r3_k5_s2_e6_c88_se0.25'],
        ['ir_r1_k3_s1_e6_c144'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=8,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mobilenet_v2(variant, channel_multiplier=1.0, depth_multiplier=1.0, fix_stem_head=False,
                      pretrained=False, **kwargs):
    """MobileNet-V2 (reference efficientnet.py:616-651)."""
    arch_def = [
        ['ds_r1_k3_s1_c16'],
        ['ir_r2_k3_s2_e6_c24'],
        ['ir_r3_k3_s2_e6_c32'],
        ['ir_r4_k3_s2_e6_c64'],
        ['ir_r3_k3_s1_e6_c96'],
        ['ir_r3_k3_s2_e6_c160'],
        ['ir_r1_k3_s1_e6_c320'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier=depth_multiplier, fix_first_last=fix_stem_head),
        num_features=1280 if fix_stem_head else max(1280, round_chs_fn(1280)),
        stem_size=32,
        fix_stem=fix_stem_head,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'relu6'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_fbnetc(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """FBNet-C (reference efficientnet.py:653-681)."""
    arch_def = [
        ['ir_r1_k3_s1_e1_c16'],
        ['ir_r1_k3_s2_e6_c24', 'ir_r2_k3_s1_e1_c24'],
        ['ir_r1_k5_s2_e6_c32', 'ir_r1_k5_s1_e3_c32', 'ir_r1_k5_s1_e6_c32', 'ir_r1_k3_s1_e6_c32'],
        ['ir_r1_k5_s2_e6_c64', 'ir_r1_k5_s1_e3_c64', 'ir_r2_k5_s1_e6_c64'],
        ['ir_r3_k5_s1_e6_c112', 'ir_r1_k5_s1_e3_c112'],
        ['ir_r4_k5_s2_e6_c184'],
        ['ir_r1_k3_s1_e6_c352'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=16,
        num_features=1984,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_spnasnet(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """Single-Path NAS (reference efficientnet.py:683-716)."""
    arch_def = [
        ['ds_r1_k3_s1_c16_noskip'],
        ['ir_r3_k3_s2_e3_c24'],
        ['ir_r1_k5_s2_e6_c40', 'ir_r3_k3_s1_e3_c40'],
        ['ir_r1_k5_s2_e6_c80', 'ir_r3_k3_s1_e3_c80'],
        ['ir_r1_k5_s1_e6_c96', 'ir_r3_k5_s1_e3_c96'],
        ['ir_r4_k5_s2_e6_c192'],
        ['ir_r1_k3_s1_e6_c320_noskip'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        stem_size=32,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_tinynet(variant, model_width=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """TinyNet (reference efficientnet.py:1188-1209)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_se0.25'], ['ir_r2_k3_s2_e6_c24_se0.25'],
        ['ir_r2_k5_s2_e6_c40_se0.25'], ['ir_r3_k3_s2_e6_c80_se0.25'],
        ['ir_r3_k5_s1_e6_c112_se0.25'], ['ir_r4_k5_s2_e6_c192_se0.25'],
        ['ir_r1_k3_s1_e6_c320_se0.25'],
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, depth_trunc='round'),
        num_features=max(1280, round_channels(1280, model_width, 8, None)),
        stem_size=32,
        fix_stem=True,
        round_chs_fn=partial(round_channels, multiplier=model_width),
        act_layer=resolve_act_layer(kwargs, 'swish'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _filter_fn(state_dict, model):
    """Reference SE layers name their convs conv_reduce/conv_expand; MixedConv
    stores its per-kernel convs as ModuleDict digits; CondConv stores flattened
    OIHW expert banks that must be re-flattened HWIO."""
    import re

    import numpy as np

    from ._torch_convert import convert_torch_state_dict
    out = {}
    done = {}
    for k, v in state_dict.items():
        k = k.replace('.se.conv_reduce.', '.se.fc1.').replace('.se.conv_expand.', '.se.fc2.')
        # MixedConv2d: conv_dw.0.weight → conv_dw.convs.0.kernel (via generic map)
        k = re.sub(r'\.(conv_pw|conv_dw|conv_pwl|conv_exp)\.(\d+)\.', r'.\1.convs.\2.', k)
        if k.endswith('.weight') and np.asarray(v).ndim == 2 and '.conv_' in k:
            # CondConv expert bank: (E, out*in/g*kh*kw) OIHW-flat → HWIO-flat;
            # final key keeps the torch name (our CondConv2d param is `weight`),
            # so it bypasses the generic .weight→.kernel transpose below
            path = k[:-len('.weight')].split('.')
            mod = model
            for p in path:
                mod = mod[int(p)] if p.isdigit() else getattr(mod, p)
            kh, kw, in_g, out_ch = mod.weight_shape
            v = np.asarray(v).reshape(-1, out_ch, in_g, kh, kw).transpose(0, 3, 4, 2, 1)
            done[k] = v.reshape(v.shape[0], -1)
            continue
        out[k] = v
    converted = convert_torch_state_dict(out, model)
    converted.update(done)
    return converted


checkpoint_filter_fn = _filter_fn


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': (7, 7),
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv_stem',
        'classifier': 'classifier',
        **kwargs,
    }


# (channel_multiplier, depth_multiplier, train res, crop_pct) per B-variant —
# reference efficientnet.py compound-scaling table
_B_PARAMS = {
    'b0': (1.0, 1.0, 224, 0.875), 'b1': (1.0, 1.1, 240, 0.882),
    'b2': (1.1, 1.2, 260, 0.89), 'b3': (1.2, 1.4, 300, 0.904),
    'b4': (1.4, 1.8, 380, 0.922), 'b5': (1.6, 2.2, 456, 0.934),
    'b6': (1.8, 2.6, 528, 0.942), 'b7': (2.0, 3.1, 600, 0.949),
    'b8': (2.2, 3.6, 672, 0.954), 'l2': (4.3, 5.3, 800, 0.961),
}
_LITE_PARAMS = {
    'lite0': (1.0, 1.0, 224, 0.875), 'lite1': (1.0, 1.1, 240, 0.882),
    'lite2': (1.1, 1.2, 260, 0.89), 'lite3': (1.2, 1.4, 280, 0.904),
    'lite4': (1.4, 1.8, 300, 0.92),
}
_TF_STATS = dict(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))


def _res_cfg(res, crop, **kwargs):
    return _cfg(input_size=(3, res, res), pool_size=(res // 32, res // 32), crop_pct=crop, **kwargs)


default_cfgs = generate_default_cfgs({
    'efficientnet_b0.ra_in1k': _cfg(hf_hub_id='timm/'),
    'efficientnet_b1.ft_in1k': _res_cfg(240, 0.882, hf_hub_id='timm/'),
    'efficientnet_b2.ra_in1k': _res_cfg(256, 0.89, hf_hub_id='timm/'),
    'efficientnet_b3.ra2_in1k': _res_cfg(288, 0.904, hf_hub_id='timm/'),
    'efficientnet_b4.ra2_in1k': _res_cfg(320, 0.922, hf_hub_id='timm/'),
    'efficientnet_b5.sw_in12k_ft_in1k': _res_cfg(448, 1.0, hf_hub_id='timm/', crop_mode='squash'),
    'efficientnet_b6.untrained': _res_cfg(528, 0.942),
    'efficientnet_b7.untrained': _res_cfg(600, 0.949),
    'efficientnet_b8.untrained': _res_cfg(672, 0.954),
    'efficientnet_l2.untrained': _res_cfg(800, 0.961),
    **{f'tf_efficientnet_{v}.in1k': _res_cfg(r, c, hf_hub_id='timm/', **_TF_STATS)
       for v, (_, _, r, c) in _B_PARAMS.items() if v in ('b0', 'b1', 'b2', 'b3', 'b4', 'b5')},
    'tf_efficientnet_b6.aa_in1k': _res_cfg(528, 0.942, hf_hub_id='timm/', **_TF_STATS),
    'tf_efficientnet_b7.ra_in1k': _res_cfg(600, 0.949, hf_hub_id='timm/', **_TF_STATS),
    'tf_efficientnet_b8.ra_in1k': _res_cfg(672, 0.954, hf_hub_id='timm/', **_TF_STATS),
    'tf_efficientnet_l2.ns_jft_in1k': _res_cfg(800, 0.96, hf_hub_id='timm/', **_TF_STATS),

    'efficientnet_es.ra_in1k': _cfg(hf_hub_id='timm/'),
    'efficientnet_em.ra2_in1k': _res_cfg(240, 0.882, hf_hub_id='timm/'),
    'efficientnet_el.ra_in1k': _res_cfg(300, 0.904, hf_hub_id='timm/'),
    'tf_efficientnet_es.in1k': _cfg(hf_hub_id='timm/', **_TF_STATS),
    'tf_efficientnet_em.in1k': _res_cfg(240, 0.882, hf_hub_id='timm/', **_TF_STATS),
    'tf_efficientnet_el.in1k': _res_cfg(300, 0.904, hf_hub_id='timm/', **_TF_STATS),

    'efficientnet_lite0.ra_in1k': _cfg(hf_hub_id='timm/'),
    'efficientnet_lite1.untrained': _res_cfg(240, 0.882),
    'efficientnet_lite2.untrained': _res_cfg(260, 0.89),
    'efficientnet_lite3.untrained': _res_cfg(280, 0.904),
    'efficientnet_lite4.untrained': _res_cfg(300, 0.92),
    **{f'tf_efficientnet_{v}.in1k': _res_cfg(r, c, hf_hub_id='timm/', **_TF_STATS)
       for v, (_, _, r, c) in _LITE_PARAMS.items()},

    'efficientnetv2_rw_t.ra2_in1k': _res_cfg(224, 1.0, hf_hub_id='timm/', test_input_size=(3, 288, 288)),
    'efficientnetv2_rw_s.ra2_in1k': _res_cfg(288, 1.0, hf_hub_id='timm/', test_input_size=(3, 384, 384)),
    'efficientnetv2_rw_m.agc_in1k': _res_cfg(320, 1.0, hf_hub_id='timm/', test_input_size=(3, 416, 416)),
    'efficientnetv2_s.in1k': _res_cfg(300, 1.0, hf_hub_id='timm/', test_input_size=(3, 384, 384)),
    'efficientnetv2_m.untrained': _res_cfg(320, 1.0, test_input_size=(3, 416, 416)),
    'efficientnetv2_l.untrained': _res_cfg(384, 1.0, test_input_size=(3, 480, 480)),
    'efficientnetv2_xl.untrained': _res_cfg(384, 1.0, test_input_size=(3, 512, 512)),
    'efficientnetv2_b0.untrained': _cfg(),
    'efficientnetv2_b1.untrained': _res_cfg(240, 0.882),
    'efficientnetv2_b2.untrained': _res_cfg(260, 0.89),
    'efficientnetv2_b3.untrained': _res_cfg(288, 0.904),
    'tf_efficientnetv2_s.in1k': _res_cfg(300, 1.0, hf_hub_id='timm/', test_input_size=(3, 384, 384), **_TF_STATS),
    'tf_efficientnetv2_m.in21k_ft_in1k': _res_cfg(
        384, 1.0, hf_hub_id='timm/', test_input_size=(3, 480, 480), **_TF_STATS),
    'tf_efficientnetv2_l.in21k_ft_in1k': _res_cfg(
        384, 1.0, hf_hub_id='timm/', test_input_size=(3, 480, 480), **_TF_STATS),
    'tf_efficientnetv2_xl.in21k_ft_in1k': _res_cfg(
        384, 1.0, hf_hub_id='timm/', test_input_size=(3, 512, 512), **_TF_STATS),
    'tf_efficientnetv2_b0.in1k': _res_cfg(192, 0.875, hf_hub_id='timm/', test_input_size=(3, 224, 224), **_TF_STATS),
    'tf_efficientnetv2_b1.in1k': _res_cfg(192, 0.882, hf_hub_id='timm/', test_input_size=(3, 240, 240), **_TF_STATS),
    'tf_efficientnetv2_b2.in1k': _res_cfg(208, 0.89, hf_hub_id='timm/', test_input_size=(3, 260, 260), **_TF_STATS),
    'tf_efficientnetv2_b3.in1k': _res_cfg(240, 0.904, hf_hub_id='timm/', test_input_size=(3, 300, 300), **_TF_STATS),

    'mnasnet_050.untrained': _cfg(),
    'mnasnet_075.untrained': _cfg(),
    'mnasnet_100.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'mnasnet_140.untrained': _cfg(),
    'semnasnet_050.untrained': _cfg(),
    'semnasnet_075.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'semnasnet_100.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'semnasnet_140.untrained': _cfg(),
    'mnasnet_small.lamb_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv2_035.untrained': _cfg(),
    'mobilenetv2_050.lamb_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv2_075.untrained': _cfg(),
    'mobilenetv2_100.ra_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv2_110d.ra_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv2_120d.ra_in1k': _cfg(hf_hub_id='timm/'),
    'mobilenetv2_140.ra_in1k': _cfg(hf_hub_id='timm/'),
    'fbnetc_100.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'spnasnet_100.rmsp_in1k': _cfg(hf_hub_id='timm/'),
    'tinynet_a.in1k': _res_cfg(192, 0.875, hf_hub_id='timm/'),
    'tinynet_b.in1k': _res_cfg(188, 0.875, hf_hub_id='timm/'),
    'tinynet_c.in1k': _res_cfg(184, 0.875, hf_hub_id='timm/'),
    'tinynet_d.in1k': _res_cfg(152, 0.875, hf_hub_id='timm/'),
    'tinynet_e.in1k': _res_cfg(106, 0.875, hf_hub_id='timm/'),
    'test_efficientnet.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), crop_pct=0.95),
    'mobilenetv1_100.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=0.95, first_conv='conv_stem', classifier='classifier'),
    'mobilenetv1_100h.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=0.95, first_conv='conv_stem', classifier='classifier'),
    'mobilenetv1_125.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=1.0, first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b0_gn.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b0_g8_gn.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b0_g16_evos.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b3_gn.untrained': _cfg(input_size=(3, 288, 288), pool_size=(9, 9), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b3_g8_gn.untrained': _cfg(input_size=(3, 288, 288), pool_size=(9, 9), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 320, 320), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_blur_b0.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_es_pruned.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_el_pruned.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 300, 300), pool_size=(10, 10), crop_pct=0.904, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_cc_b0_4e.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_cc_b0_8e.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_cc_b1_8e.untrained': _cfg(input_size=(3, 240, 240), pool_size=(8, 8), crop_pct=0.882, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'gc_efficientnetv2_rw_t.agc_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 288, 288), first_conv='conv_stem', classifier='classifier'),
    'tf_efficientnet_cc_b0_4e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='conv_stem', classifier='classifier'),
    'tf_efficientnet_cc_b0_8e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='conv_stem', classifier='classifier'),
    'tf_efficientnet_cc_b1_8e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), pool_size=(8, 8), crop_pct=0.882, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_x_b3.untrained': _cfg(input_size=(3, 288, 288), pool_size=(9, 9), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_x_b5.sw_r448_e450_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448), pool_size=(14, 14), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 576, 576), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_h_b5.sw_r448_e450_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 448, 448), pool_size=(14, 14), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), test_input_size=(3, 576, 576), first_conv='conv_stem', classifier='classifier'),
    'mixnet_s.ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mixnet_m.ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mixnet_l.ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mixnet_xl.ra_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mixnet_xxl.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'tf_mixnet_s.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'tf_mixnet_m.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'tf_mixnet_l.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.875, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mobilenet_edgetpu_100.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mobilenet_edgetpu_v2_xs.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mobilenet_edgetpu_v2_s.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'mobilenet_edgetpu_v2_m.ra4_e3600_r224_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), test_input_size=(3, 256, 256), test_crop_pct=0.95, first_conv='conv_stem', classifier='classifier'),
    'mobilenet_edgetpu_v2_l.untrained': _cfg(input_size=(3, 224, 224), pool_size=(7, 7), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'test_efficientnet_gn.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), pool_size=(5, 5), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='conv_stem', classifier='classifier'),
    'test_efficientnet_ln.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), pool_size=(5, 5), crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), first_conv='conv_stem', classifier='classifier'),
    'test_efficientnet_evos.r160_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 160, 160), pool_size=(5, 5), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), first_conv='conv_stem', classifier='classifier'),
    'efficientnet_b1_pruned.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 240, 240), pool_size=(8, 8), crop_pct=0.882, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'efficientnet_b2_pruned.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 260, 260), pool_size=(9, 9), crop_pct=0.89, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'efficientnet_b3_pruned.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 300, 300), pool_size=(10, 10), crop_pct=0.904, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
})


def _register_effnet_b(name: str):
    cm, dm, _, _ = _B_PARAMS[name]

    def base(pretrained=False, **kwargs):
        return _gen_efficientnet(f'efficientnet_{name}', cm, dm, pretrained=pretrained, **kwargs)

    def tf(pretrained=False, **kwargs):
        kwargs.setdefault('bn_eps', 1e-3)
        kwargs.setdefault('pad_type', 'same')
        return _gen_efficientnet(f'tf_efficientnet_{name}', cm, dm, pretrained=pretrained, **kwargs)

    base.__name__ = f'efficientnet_{name}'
    base.__doc__ = f'EfficientNet-{name.upper()} (reference efficientnet.py entrypoints)'
    tf.__name__ = f'tf_efficientnet_{name}'
    tf.__doc__ = f'EfficientNet-{name.upper()}, TF-origin weights (SAME padding, bn_eps=1e-3)'
    register_model(base)
    register_model(tf)


for _b in _B_PARAMS:
    _register_effnet_b(_b)


def _register_effnet_lite(name: str):
    cm, dm, _, _ = _LITE_PARAMS[name]

    def base(pretrained=False, **kwargs):
        return _gen_efficientnet_lite(f'efficientnet_{name}', cm, dm, pretrained, **kwargs)

    def tf(pretrained=False, **kwargs):
        kwargs.setdefault('bn_eps', 1e-3)
        kwargs.setdefault('pad_type', 'same')
        return _gen_efficientnet_lite(f'tf_efficientnet_{name}', cm, dm, pretrained, **kwargs)

    base.__name__ = f'efficientnet_{name}'
    base.__doc__ = f'EfficientNet-{name} (reference efficientnet.py entrypoints)'
    tf.__name__ = f'tf_efficientnet_{name}'
    tf.__doc__ = f'EfficientNet-{name}, TF-origin weights (SAME padding, bn_eps=1e-3)'
    register_model(base)
    register_model(tf)


for _l in _LITE_PARAMS:
    _register_effnet_lite(_l)


@register_model
def efficientnet_es(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet_edge('efficientnet_es', 1.0, 1.0, pretrained, **kwargs)


@register_model
def efficientnet_em(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet_edge('efficientnet_em', 1.0, 1.1, pretrained, **kwargs)


@register_model
def efficientnet_el(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnet_edge('efficientnet_el', 1.2, 1.4, pretrained, **kwargs)


@register_model
def tf_efficientnet_es(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnet_edge('tf_efficientnet_es', 1.0, 1.0, pretrained, **kwargs)


@register_model
def tf_efficientnet_em(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnet_edge('tf_efficientnet_em', 1.0, 1.1, pretrained, **kwargs)


@register_model
def tf_efficientnet_el(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnet_edge('tf_efficientnet_el', 1.2, 1.4, pretrained, **kwargs)


@register_model
def efficientnetv2_rw_t(pretrained=False, **kwargs) -> EfficientNet:
    """V2 Tiny: a 0.8/0.9-scaled v2-S (reference efficientnet.py:2367)."""
    return _gen_efficientnetv2_s(
        'efficientnetv2_rw_t', channel_multiplier=0.8, depth_multiplier=0.9, rw=False,
        pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_rw_s(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_s('efficientnetv2_rw_s', rw=True, pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_rw_m(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_s(
        'efficientnetv2_rw_m', channel_multiplier=1.2, depth_multiplier=(1.2,) * 4 + (1.6,) * 2,
        rw=True, pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_s(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_s('efficientnetv2_s', pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_m(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_m('efficientnetv2_m', pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_l(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_l('efficientnetv2_l', pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_xl(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_xl('efficientnetv2_xl', pretrained=pretrained, **kwargs)


@register_model
def efficientnetv2_b0(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_base('efficientnetv2_b0', 1.0, 1.0, pretrained, **kwargs)


@register_model
def efficientnetv2_b1(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_base('efficientnetv2_b1', 1.0, 1.1, pretrained, **kwargs)


@register_model
def efficientnetv2_b2(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_base('efficientnetv2_b2', 1.1, 1.2, pretrained, **kwargs)


@register_model
def efficientnetv2_b3(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_efficientnetv2_base('efficientnetv2_b3', 1.2, 1.4, pretrained, **kwargs)


@register_model
def tf_efficientnetv2_s(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_s('tf_efficientnetv2_s', pretrained=pretrained, **kwargs)


@register_model
def tf_efficientnetv2_m(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_m('tf_efficientnetv2_m', pretrained=pretrained, **kwargs)


@register_model
def tf_efficientnetv2_l(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_l('tf_efficientnetv2_l', pretrained=pretrained, **kwargs)


@register_model
def tf_efficientnetv2_xl(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_xl('tf_efficientnetv2_xl', pretrained=pretrained, **kwargs)


@register_model
def tf_efficientnetv2_b0(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_base('tf_efficientnetv2_b0', 1.0, 1.0, pretrained, **kwargs)


@register_model
def tf_efficientnetv2_b1(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_base('tf_efficientnetv2_b1', 1.0, 1.1, pretrained, **kwargs)


@register_model
def tf_efficientnetv2_b2(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_base('tf_efficientnetv2_b2', 1.1, 1.2, pretrained, **kwargs)


@register_model
def tf_efficientnetv2_b3(pretrained=False, **kwargs) -> EfficientNet:
    kwargs.setdefault('bn_eps', 1e-3)
    kwargs.setdefault('pad_type', 'same')
    return _gen_efficientnetv2_base('tf_efficientnetv2_b3', 1.2, 1.4, pretrained, **kwargs)


@register_model
def mnasnet_050(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_b1('mnasnet_050', 0.5, pretrained=pretrained, **kwargs)


@register_model
def mnasnet_075(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_b1('mnasnet_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def mnasnet_100(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_b1('mnasnet_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def mnasnet_140(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_b1('mnasnet_140', 1.4, pretrained=pretrained, **kwargs)


@register_model
def semnasnet_050(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_a1('semnasnet_050', 0.5, pretrained=pretrained, **kwargs)


@register_model
def semnasnet_075(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_a1('semnasnet_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def semnasnet_100(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_a1('semnasnet_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def semnasnet_140(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_a1('semnasnet_140', 1.4, pretrained=pretrained, **kwargs)


@register_model
def mnasnet_small(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mnasnet_small('mnasnet_small', 1.0, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_035(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2('mobilenetv2_035', 0.35, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_050(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2('mobilenetv2_050', 0.5, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_075(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2('mobilenetv2_075', 0.75, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_100(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2('mobilenetv2_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_110d(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2(
        'mobilenetv2_110d', 1.1, depth_multiplier=1.2, fix_stem_head=True, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_120d(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2(
        'mobilenetv2_120d', 1.2, depth_multiplier=1.4, fix_stem_head=True, pretrained=pretrained, **kwargs)


@register_model
def mobilenetv2_140(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_mobilenet_v2('mobilenetv2_140', 1.4, pretrained=pretrained, **kwargs)


@register_model
def fbnetc_100(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_fbnetc('fbnetc_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def spnasnet_100(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_spnasnet('spnasnet_100', 1.0, pretrained=pretrained, **kwargs)


@register_model
def tinynet_a(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_tinynet('tinynet_a', 1.0, 1.2, pretrained=pretrained, **kwargs)


@register_model
def tinynet_b(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_tinynet('tinynet_b', 0.75, 1.1, pretrained=pretrained, **kwargs)


@register_model
def tinynet_c(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_tinynet('tinynet_c', 0.54, 0.85, pretrained=pretrained, **kwargs)


@register_model
def tinynet_d(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_tinynet('tinynet_d', 0.54, 0.695, pretrained=pretrained, **kwargs)


@register_model
def tinynet_e(pretrained=False, **kwargs) -> EfficientNet:
    return _gen_tinynet('tinynet_e', 0.51, 0.6, pretrained=pretrained, **kwargs)


def _gen_test_efficientnet(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """Minimal test EfficientNet generator (reference efficientnet.py:1300-1321)."""
    arch_def = [
        ['cn_r1_k3_s1_e1_c16_skip'],
        ['er_r1_k3_s2_e4_c24'],
        ['er_r1_k3_s2_e4_c32'],
        ['ir_r1_k3_s2_e4_c48_se0.25'],
        ['ir_r1_k3_s2_e4_c64_se0.25'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier, round_limit=0.)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=round_chs_fn(256),
        stem_size=24,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mobilenet_v1(
        variant, channel_multiplier=1.0, depth_multiplier=1.0,
        group_size=None, fix_stem_head=False, head_conv=False, pretrained=False, **kwargs):
    """MobileNet-V1 (reference efficientnet.py:580-613)."""
    arch_def = [
        ['dsa_r1_k3_s1_c64'],
        ['dsa_r2_k3_s2_c128'],
        ['dsa_r2_k3_s2_c256'],
        ['dsa_r6_k3_s2_c512'],
        ['dsa_r2_k3_s2_c1024'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    head_features = (1024 if fix_stem_head else max(1024, round_chs_fn(1024))) if head_conv else 0
    model_kwargs = dict(
        block_args=decode_arch_def(
            arch_def, depth_multiplier=depth_multiplier, fix_first_last=fix_stem_head,
            group_size=group_size),
        num_features=head_features,
        stem_size=32,
        fix_stem=fix_stem_head,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'relu6'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnet_condconv(
        variant, channel_multiplier=1.0, depth_multiplier=1.0, experts_multiplier=1,
        pretrained=False, **kwargs):
    """EfficientNet-CondConv (reference efficientnet.py:800-830)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16_se0.25'],
        ['ir_r2_k3_s2_e6_c24_se0.25'],
        ['ir_r2_k5_s2_e6_c40_se0.25'],
        ['ir_r3_k3_s2_e6_c80_se0.25'],
        ['ir_r3_k5_s1_e6_c112_se0.25_cc4'],
        ['ir_r4_k5_s2_e6_c192_se0.25_cc4'],
        ['ir_r1_k3_s1_e6_c320_se0.25_cc4'],
    ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, experts_multiplier=experts_multiplier),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'swish'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_efficientnet_x(
        variant, channel_multiplier=1.0, depth_multiplier=1.0, channel_divisor=8,
        group_size=None, version=1, pretrained=False, **kwargs):
    """EfficientNet-X (reference efficientnet.py:1039-1120): edge-residual
    early stages w/ relu, depthwise-separable-style later stages w/ silu."""
    if version == 1:
        arch_def = [
            ['ds_r1_k3_s1_e1_c16_se0.25_d1'],
            ['er_r2_k3_s2_e6_c24_se0.25_nre'],
            ['er_r2_k5_s2_e6_c40_se0.25_nre'],
            ['ir_r3_k3_s2_e6_c80_se0.25'],
            ['ir_r3_k5_s1_e6_c112_se0.25'],
            ['ir_r4_k5_s2_e6_c192_se0.25'],
            ['ir_r1_k3_s1_e6_c320_se0.25'],
        ]
    else:
        arch_def = [
            ['ds_r1_k3_s1_e1_c16_se0.25_d1'],
            ['er_r2_k3_s2_e4_c24_se0.25_nre'],
            ['er_r2_k5_s2_e4_c40_se0.25_nre'],
            ['ir_r3_k3_s2_e4_c80_se0.25'],
            ['ir_r3_k5_s1_e6_c112_se0.25'],
            ['ir_r4_k5_s2_e6_c192_se0.25'],
            ['ir_r1_k3_s1_e6_c320_se0.25'],
        ]
    round_chs_fn = partial(round_channels, multiplier=channel_multiplier, divisor=channel_divisor)
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, group_size=group_size),
        num_features=round_chs_fn(1280),
        stem_size=32,
        round_chs_fn=round_chs_fn,
        act_layer=resolve_act_layer(kwargs, 'silu'),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mixnet_s(variant, channel_multiplier=1.0, pretrained=False, **kwargs):
    """MixNet Small — mixed (grouped multi-size) depthwise kernels
    (reference efficientnet.py:1122-1153)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c16'],  # relu
        ['ir_r1_k3_a1.1_p1.1_s2_e6_c24', 'ir_r1_k3_a1.1_p1.1_s1_e3_c24'],  # relu
        ['ir_r1_k3.5.7_s2_e6_c40_se0.5_nsw', 'ir_r3_k3.5_a1.1_p1.1_s1_e6_c40_se0.5_nsw'],  # swish
        ['ir_r1_k3.5.7_p1.1_s2_e6_c80_se0.25_nsw', 'ir_r2_k3.5_p1.1_s1_e6_c80_se0.25_nsw'],  # swish
        ['ir_r1_k3.5.7_a1.1_p1.1_s1_e6_c120_se0.5_nsw', 'ir_r2_k3.5.7.9_a1.1_p1.1_s1_e3_c120_se0.5_nsw'],  # swish
        ['ir_r1_k3.5.7.9.11_s2_e6_c200_se0.5_nsw', 'ir_r2_k3.5.7.9_p1.1_s1_e6_c200_se0.5_nsw'],  # swish
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def),
        num_features=1536,
        stem_size=16,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mixnet_m(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """MixNet Medium/Large/XL (reference efficientnet.py:1155-1188)."""
    arch_def = [
        ['ds_r1_k3_s1_e1_c24'],  # relu
        ['ir_r1_k3.5.7_a1.1_p1.1_s2_e6_c32', 'ir_r1_k3_a1.1_p1.1_s1_e3_c32'],  # relu
        ['ir_r1_k3.5.7.9_s2_e6_c40_se0.5_nsw', 'ir_r3_k3.5_a1.1_p1.1_s1_e6_c40_se0.5_nsw'],  # swish
        ['ir_r1_k3.5.7_s2_e6_c80_se0.25_nsw', 'ir_r3_k3.5.7.9_a1.1_p1.1_s1_e6_c80_se0.25_nsw'],  # swish
        ['ir_r1_k3_s1_e6_c120_se0.5_nsw', 'ir_r3_k3.5.7.9_a1.1_p1.1_s1_e3_c120_se0.5_nsw'],  # swish
        ['ir_r1_k3.5.7.9_s2_e6_c200_se0.5_nsw', 'ir_r3_k3.5.7.9_p1.1_s1_e6_c200_se0.5_nsw'],  # swish
    ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier, depth_trunc='round'),
        num_features=1536,
        stem_size=24,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


def _gen_mobilenet_edgetpu(variant, channel_multiplier=1.0, depth_multiplier=1.0, pretrained=False, **kwargs):
    """MobileNet-EdgeTPU v1/v2 (reference efficientnet.py:1211-1298)."""
    if 'edgetpu_v2' in variant:
        stem_size = 64
        stem_kernel_size = 5
        group_size = 64
        num_features = 1280
        act_layer = resolve_act_layer(kwargs, 'relu')

        def _arch_def(chs, group_size):
            return [
                [f'cn_r1_k1_s1_c{chs[0]}'],
                [f'er_r1_k3_s2_e8_c{chs[1]}', f'er_r1_k3_s1_e4_gs{group_size}_c{chs[1]}'],
                [
                    f'er_r1_k3_s2_e8_c{chs[2]}',
                    f'er_r1_k3_s1_e4_gs{group_size}_c{chs[2]}',
                    f'er_r1_k3_s1_e4_c{chs[2]}',
                    f'er_r1_k3_s1_e4_gs{group_size}_c{chs[2]}',
                ],
                [f'er_r1_k3_s2_e8_c{chs[3]}', f'ir_r3_k3_s1_e4_c{chs[3]}'],
                [f'ir_r1_k3_s1_e8_c{chs[4]}', f'ir_r3_k3_s1_e4_c{chs[4]}'],
                [f'ir_r1_k3_s2_e8_c{chs[5]}', f'ir_r3_k3_s1_e4_c{chs[5]}'],
                [f'ir_r1_k3_s1_e8_c{chs[6]}'],
            ]

        if 'edgetpu_v2_xs' in variant:
            stem_size = 32
            stem_kernel_size = 3
            channels = [16, 32, 48, 96, 144, 160, 192]
        elif 'edgetpu_v2_s' in variant:
            channels = [24, 48, 64, 128, 160, 192, 256]
        elif 'edgetpu_v2_m' in variant:
            channels = [32, 64, 80, 160, 192, 240, 320]
            num_features = 1344
        elif 'edgetpu_v2_l' in variant:
            stem_kernel_size = 7
            group_size = 128
            channels = [32, 64, 96, 192, 240, 256, 384]
            num_features = 1408
        else:
            raise AssertionError(f'unknown edgetpu v2 variant {variant}')
        arch_def = _arch_def(channels, group_size)
    else:  # v1
        stem_size = 32
        stem_kernel_size = 3
        num_features = 1280
        act_layer = resolve_act_layer(kwargs, 'relu')
        arch_def = [
            ['cn_r1_k1_s1_c16'],
            ['er_r1_k3_s2_e8_c32', 'er_r3_k3_s1_e4_c32'],
            ['er_r1_k3_s2_e8_c48', 'er_r3_k3_s1_e4_c48'],
            ['ir_r1_k3_s2_e8_c96', 'ir_r3_k3_s1_e4_c96'],
            ['ir_r1_k3_s1_e8_c96_noskip', 'ir_r3_k3_s1_e4_c96'],
            ['ir_r1_k5_s2_e8_c160', 'ir_r3_k5_s1_e4_c160'],
            ['ir_r1_k3_s1_e8_c192'],
        ]
    model_kwargs = dict(
        block_args=decode_arch_def(arch_def, depth_multiplier),
        num_features=num_features,
        stem_size=stem_size,
        stem_kernel_size=stem_kernel_size,
        round_chs_fn=partial(round_channels, multiplier=channel_multiplier),
        act_layer=act_layer,
        **kwargs,
    )
    return _create_effnet(variant, pretrained, **model_kwargs)


@register_model
def test_efficientnet(pretrained=False, **kwargs) -> EfficientNet:
    """Tiny fixture (reference efficientnet.py:2902)."""
    return _gen_test_efficientnet('test_efficientnet', pretrained=pretrained, **kwargs)


@register_model
def mobilenetv1_100(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet V1 """
    model = _gen_mobilenet_v1('mobilenetv1_100', 1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv1_100h(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet V1 """
    model = _gen_mobilenet_v1('mobilenetv1_100h', 1.0, head_conv=True, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenetv1_125(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet V1 """
    model = _gen_mobilenet_v1('mobilenetv1_125', 1.25, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b0_gn(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B0 + GroupNorm"""
    model = _gen_efficientnet(
        'efficientnet_b0_gn', norm_layer=partial(GroupNormAct, group_size=8), pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b0_g8_gn(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B0 w/ group conv + GroupNorm"""
    model = _gen_efficientnet(
        'efficientnet_b0_g8_gn', group_size=8, norm_layer=partial(GroupNormAct, group_size=8),
        pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b0_g16_evos(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B0 w/ group 16 conv + EvoNorm"""
    model = _gen_efficientnet(
        'efficientnet_b0_g16_evos', group_size=16, channel_divisor=16,
        pretrained=pretrained, **kwargs) #norm_layer=partial(EvoNorm2dS0, group_size=16),
    return model


@register_model
def efficientnet_b3_gn(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B3 w/ GroupNorm """
    # NOTE for train, drop_rate should be 0.3, drop_path_rate should be 0.2
    model = _gen_efficientnet(
        'efficientnet_b3_gn', channel_multiplier=1.2, depth_multiplier=1.4, channel_divisor=16,
        norm_layer=partial(GroupNormAct, group_size=16), pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b3_g8_gn(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B3 w/ grouped conv + BN"""
    # NOTE for train, drop_rate should be 0.3, drop_path_rate should be 0.2
    model = _gen_efficientnet(
        'efficientnet_b3_g8_gn', channel_multiplier=1.2, depth_multiplier=1.4, group_size=8, channel_divisor=16,
        norm_layer=partial(GroupNormAct, group_size=16), pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_blur_b0(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B0 w/ BlurPool """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    model = _gen_efficientnet(
        'efficientnet_blur_b0', channel_multiplier=1.0, depth_multiplier=1.0, pretrained=pretrained,
        aa_layer='blurpc', **kwargs
    )
    return model


@register_model
def efficientnet_es_pruned(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-Edge Small Pruned. For more info: https://github.com/DeGirum/pruned-models/releases/tag/efficientnet_v1.0"""
    model = _gen_efficientnet_edge(
        'efficientnet_es_pruned', channel_multiplier=1.0, depth_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_el_pruned(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-Edge-Large pruned. For more info: https://github.com/DeGirum/pruned-models/releases/tag/efficientnet_v1.0"""
    model = _gen_efficientnet_edge(
        'efficientnet_el_pruned', channel_multiplier=1.2, depth_multiplier=1.4, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_cc_b0_4e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B0 w/ 8 Experts """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    model = _gen_efficientnet_condconv(
        'efficientnet_cc_b0_4e', channel_multiplier=1.0, depth_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_cc_b0_8e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B0 w/ 8 Experts """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    model = _gen_efficientnet_condconv(
        'efficientnet_cc_b0_8e', channel_multiplier=1.0, depth_multiplier=1.0, experts_multiplier=2,
        pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_cc_b1_8e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B1 w/ 8 Experts """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    model = _gen_efficientnet_condconv(
        'efficientnet_cc_b1_8e', channel_multiplier=1.0, depth_multiplier=1.1, experts_multiplier=2,
        pretrained=pretrained, **kwargs)
    return model


@register_model
def gc_efficientnetv2_rw_t(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-V2 Tiny w/ Global Context Attn (Custom variant, tiny not in paper). """
    model = _gen_efficientnetv2_s(
        'gc_efficientnetv2_rw_t', channel_multiplier=0.8, depth_multiplier=0.9,
        rw=False, se_layer='gc', pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_efficientnet_cc_b0_4e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B0 w/ 4 Experts. Tensorflow compatible variant """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_efficientnet_condconv(
        'tf_efficientnet_cc_b0_4e', channel_multiplier=1.0, depth_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_efficientnet_cc_b0_8e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B0 w/ 8 Experts. Tensorflow compatible variant """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_efficientnet_condconv(
        'tf_efficientnet_cc_b0_8e', channel_multiplier=1.0, depth_multiplier=1.0, experts_multiplier=2,
        pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_efficientnet_cc_b1_8e(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-CondConv-B1 w/ 8 Experts. Tensorflow compatible variant """
    # NOTE for train, drop_rate should be 0.2, drop_path_rate should be 0.2
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_efficientnet_condconv(
        'tf_efficientnet_cc_b1_8e', channel_multiplier=1.0, depth_multiplier=1.1, experts_multiplier=2,
        pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_x_b3(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B3 """
    # NOTE for train, drop_rate should be 0.3, drop_path_rate should be 0.2
    model = _gen_efficientnet_x(
        'efficientnet_x_b3', channel_multiplier=1.2, depth_multiplier=1.4, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_x_b5(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B5 """
    model = _gen_efficientnet_x(
        'efficientnet_x_b5', channel_multiplier=1.6, depth_multiplier=2.2, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_h_b5(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B5 """
    model = _gen_efficientnet_x(
        'efficientnet_h_b5', channel_multiplier=1.92, depth_multiplier=2.2, version=2, pretrained=pretrained, **kwargs)
    return model


@register_model
def mixnet_s(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Small model.
    """
    model = _gen_mixnet_s(
        'mixnet_s', channel_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mixnet_m(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Medium model.
    """
    model = _gen_mixnet_m(
        'mixnet_m', channel_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def mixnet_l(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Large model.
    """
    model = _gen_mixnet_m(
        'mixnet_l', channel_multiplier=1.3, pretrained=pretrained, **kwargs)
    return model


@register_model
def mixnet_xl(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Extra-Large model.
    Not a paper spec, experimental def by RW w/ depth scaling.
    """
    model = _gen_mixnet_m(
        'mixnet_xl', channel_multiplier=1.6, depth_multiplier=1.2, pretrained=pretrained, **kwargs)
    return model


@register_model
def mixnet_xxl(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Double Extra Large model.
    Not a paper spec, experimental def by RW w/ depth scaling.
    """
    model = _gen_mixnet_m(
        'mixnet_xxl', channel_multiplier=2.4, depth_multiplier=1.3, pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_mixnet_s(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Small model. Tensorflow compatible variant
    """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_mixnet_s(
        'tf_mixnet_s', channel_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_mixnet_m(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Medium model. Tensorflow compatible variant
    """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_mixnet_m(
        'tf_mixnet_m', channel_multiplier=1.0, pretrained=pretrained, **kwargs)
    return model


@register_model
def tf_mixnet_l(pretrained=False, **kwargs) -> EfficientNet:
    """Creates a MixNet Large model. Tensorflow compatible variant
    """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_mixnet_m(
        'tf_mixnet_l', channel_multiplier=1.3, pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenet_edgetpu_100(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet-EdgeTPU-v1 100. """
    model = _gen_mobilenet_edgetpu('mobilenet_edgetpu_100', pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenet_edgetpu_v2_xs(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet-EdgeTPU-v2 Extra Small. """
    model = _gen_mobilenet_edgetpu('mobilenet_edgetpu_v2_xs', pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenet_edgetpu_v2_s(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet-EdgeTPU-v2 Small. """
    model = _gen_mobilenet_edgetpu('mobilenet_edgetpu_v2_s', pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenet_edgetpu_v2_m(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet-EdgeTPU-v2 Medium. """
    model = _gen_mobilenet_edgetpu('mobilenet_edgetpu_v2_m', pretrained=pretrained, **kwargs)
    return model


@register_model
def mobilenet_edgetpu_v2_l(pretrained=False, **kwargs) -> EfficientNet:
    """ MobileNet-EdgeTPU-v2 Large. """
    model = _gen_mobilenet_edgetpu('mobilenet_edgetpu_v2_l', pretrained=pretrained, **kwargs)
    return model


@register_model
def test_efficientnet_gn(pretrained=False, **kwargs) -> EfficientNet:

    model = _gen_test_efficientnet(
        'test_efficientnet_gn',
        pretrained=pretrained,
        norm_layer=kwargs.pop('norm_layer', partial(GroupNormAct, group_size=8)),
        **kwargs
    )
    return model


@register_model
def test_efficientnet_ln(pretrained=False, **kwargs) -> EfficientNet:
    model = _gen_test_efficientnet(
        'test_efficientnet_ln',
        pretrained=pretrained,
        norm_layer=kwargs.pop('norm_layer', LayerNormAct2d),
        **kwargs
    )
    return model


@register_model
def test_efficientnet_evos(pretrained=False, **kwargs) -> EfficientNet:
    model = _gen_test_efficientnet(
        'test_efficientnet_evos',
        pretrained=pretrained,
        norm_layer=kwargs.pop('norm_layer', partial(EvoNorm2dS0, group_size=8)),
        **kwargs
    )
    return model


@register_model
def efficientnet_b1_pruned(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B1 Pruned. The pruning has been obtained using https://arxiv.org/pdf/2002.08258.pdf  """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    variant = 'efficientnet_b1_pruned'
    model = _gen_efficientnet(
        variant, channel_multiplier=1.0, depth_multiplier=1.1, pruned=True, pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b2_pruned(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B2 Pruned. The pruning has been obtained using https://arxiv.org/pdf/2002.08258.pdf """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_efficientnet(
        'efficientnet_b2_pruned', channel_multiplier=1.1, depth_multiplier=1.2, pruned=True,
        pretrained=pretrained, **kwargs)
    return model


@register_model
def efficientnet_b3_pruned(pretrained=False, **kwargs) -> EfficientNet:
    """ EfficientNet-B3 Pruned. The pruning has been obtained using https://arxiv.org/pdf/2002.08258.pdf """
    kwargs.setdefault('bn_eps', BN_EPS_TF_DEFAULT)
    kwargs.setdefault('pad_type', 'same')
    model = _gen_efficientnet(
        'efficientnet_b3_pruned', channel_multiplier=1.2, depth_multiplier=1.4, pruned=True,
        pretrained=pretrained, **kwargs)
    return model
