"""VoVNet V1/V2 (reference: timm/models/vovnet.py:1-559), TPU-native NHWC.

One-Shot-Aggregation (OSA) blocks: a chain of 3x3 (or separable) convs whose
every intermediate output is concatenated and fused with a 1x1 conv; V2 adds
identity residuals and effective-SE attention. The concat is a pure layout op
in NHWC, and the 1x1 fuse is a single big MXU matmul over all branches.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNormAct2d, ClassifierHead, ConvNormAct, DropPath, SeparableConvNormAct,
    calculate_drop_path_rates, create_attn,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['VovNet']


def _max_pool2d_ceil(x, kernel=3, stride=2):
    """Torch MaxPool2d(3, 2, ceil_mode=True): pad right/bottom so every
    window start inside the input is kept."""
    B, H, W, C = x.shape
    out_h = -(-(H - kernel) // stride) + 1
    out_w = -(-(W - kernel) // stride) + 1
    pad_h = max(0, (out_h - 1) * stride + kernel - H)
    pad_w = max(0, (out_w - 1) * stride + kernel - W)
    neg = -jnp.inf if x.dtype == jnp.float32 else jnp.finfo(x.dtype).min
    x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)), constant_values=neg)
    return jax.lax.reduce_window(
        x, neg, jax.lax.max, (1, kernel, kernel, 1), (1, stride, stride, 1), 'VALID')


class OsaBlock(nnx.Module):
    """(reference vovnet.py:34-90)."""

    def __init__(self, in_chs, mid_chs, out_chs, layer_per_block, residual=False,
                 depthwise=False, attn='', norm_layer=BatchNormAct2d, act_layer='relu',
                 drop_path=0.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        conv_kwargs = dict(norm_layer=norm_layer, act_layer=act_layer,
                           dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.residual = residual
        self.depthwise = depthwise
        next_in_chs = in_chs
        if depthwise and next_in_chs != mid_chs:
            assert not residual
            self.conv_reduction = ConvNormAct(next_in_chs, mid_chs, 1, **conv_kwargs)
        else:
            self.conv_reduction = None
        mid_convs = []
        for i in range(layer_per_block):
            if depthwise:
                mid_convs.append(SeparableConvNormAct(mid_chs, mid_chs, **conv_kwargs))
            else:
                mid_convs.append(ConvNormAct(next_in_chs, mid_chs, 3, **conv_kwargs))
            next_in_chs = mid_chs
        self.conv_mid = nnx.List(mid_convs)
        next_in_chs = in_chs + layer_per_block * mid_chs
        self.conv_concat = ConvNormAct(next_in_chs, out_chs, **conv_kwargs)
        self.attn = create_attn(attn, out_chs, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if attn else None
        self.drop_path = DropPath(drop_path, rngs=rngs) if drop_path else None

    def __call__(self, x):
        outputs = [x]
        if self.conv_reduction is not None:
            x = self.conv_reduction(x)
        for conv in self.conv_mid:
            x = conv(x)
            outputs.append(x)
        x = jnp.concatenate(outputs, axis=-1)
        x = self.conv_concat(x)
        if self.attn is not None:
            x = self.attn(x)
        if self.drop_path is not None:
            x = self.drop_path(x)
        if self.residual:
            x = x + outputs[0]
        return x


class OsaStage(nnx.Module):
    """(reference vovnet.py:92-143)."""

    def __init__(self, in_chs, mid_chs, out_chs, block_per_stage, layer_per_block,
                 downsample=True, residual=True, depthwise=False, attn='ese',
                 norm_layer=BatchNormAct2d, act_layer='relu', drop_path_rates=None,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.grad_checkpointing = False
        self.downsample = downsample
        blocks = []
        for i in range(block_per_stage):
            last_block = i == block_per_stage - 1
            dpr = drop_path_rates[i] if drop_path_rates is not None else 0.0
            blocks.append(OsaBlock(
                in_chs, mid_chs, out_chs, layer_per_block,
                residual=residual and i > 0,
                depthwise=depthwise,
                attn=attn if last_block else '',
                norm_layer=norm_layer, act_layer=act_layer, drop_path=dpr,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs))
            in_chs = out_chs
        self.blocks = nnx.List(blocks)

    def __call__(self, x):
        if self.downsample:
            x = _max_pool2d_ceil(x, 3, 2)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x


class VovNet(nnx.Module):
    """(reference vovnet.py:145-353)."""

    def __init__(
            self,
            cfg: dict,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            output_stride: int = 32,
            norm_layer=BatchNormAct2d,
            act_layer='relu',
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
            **kwargs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        cfg = dict(cfg, **kwargs)
        stem_stride = cfg.get('stem_stride', 4)
        stem_chs = cfg['stem_chs']
        stage_conv_chs = cfg['stage_conv_chs']
        stage_out_chs = cfg['stage_out_chs']
        block_per_stage = cfg['block_per_stage']
        layer_per_block = cfg['layer_per_block']
        conv_kwargs = dict(norm_layer=norm_layer, act_layer=act_layer,
                           dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        last_stem_stride = stem_stride // 2
        conv_type = SeparableConvNormAct if cfg['depthwise'] else ConvNormAct
        self.stem = nnx.List([
            ConvNormAct(in_chans, stem_chs[0], 3, stride=2, **conv_kwargs),
            conv_type(stem_chs[0], stem_chs[1], 3, stride=1, **conv_kwargs),
            conv_type(stem_chs[1], stem_chs[2], 3, stride=last_stem_stride, **conv_kwargs),
        ])
        self.feature_info = [dict(
            num_chs=stem_chs[1], reduction=2, module=f'stem.{1 if stem_stride == 4 else 2}')]
        current_stride = stem_stride

        stage_dpr = calculate_drop_path_rates(drop_path_rate, block_per_stage, stagewise=True)
        in_ch_list = stem_chs[-1:] + stage_out_chs[:-1]
        stage_args = dict(residual=cfg['residual'], depthwise=cfg['depthwise'], attn=cfg['attn'], **conv_kwargs)
        stages = []
        for i in range(4):
            downsample = stem_stride == 2 or i > 0
            stages.append(OsaStage(
                in_ch_list[i], stage_conv_chs[i], stage_out_chs[i], block_per_stage[i],
                layer_per_block, downsample=downsample, drop_path_rates=stage_dpr[i], **stage_args))
            self.num_features = stage_out_chs[i]
            current_stride *= 2 if downsample else 1
            self.feature_info += [dict(num_chs=self.num_features, reduction=current_stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)

        self.head_hidden_size = self.num_features
        self.head = ClassifierHead(
            self.num_features, num_classes, pool_type=global_pool, drop_rate=drop_rate,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=r'^stages\.(\d+)' if coarse else r'^stages\.(\d+).blocks\.(\d+)',
        )

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        for m in self.stem:
            x = m(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        for m in self.stem:
            x = m(x)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


# stage cfg tables (reference vovnet.py:355-461)
model_cfgs = dict(
    vovnet39a=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 1, 2, 2],
        residual=False,
        depthwise=False,
        attn='',
    ),
    vovnet57a=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 1, 4, 3],
        residual=False,
        depthwise=False,
        attn='',
    ),
    ese_vovnet19b_slim_dw=dict(
        stem_chs=[64, 64, 64],
        stage_conv_chs=[64, 80, 96, 112],
        stage_out_chs=[112, 256, 384, 512],
        layer_per_block=3,
        block_per_stage=[1, 1, 1, 1],
        residual=True,
        depthwise=True,
        attn='ese',
    ),
    ese_vovnet19b_dw=dict(
        stem_chs=[64, 64, 64],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=3,
        block_per_stage=[1, 1, 1, 1],
        residual=True,
        depthwise=True,
        attn='ese',
    ),
    ese_vovnet19b_slim=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[64, 80, 96, 112],
        stage_out_chs=[112, 256, 384, 512],
        layer_per_block=3,
        block_per_stage=[1, 1, 1, 1],
        residual=True,
        depthwise=False,
        attn='ese',
    ),
    ese_vovnet19b=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=3,
        block_per_stage=[1, 1, 1, 1],
        residual=True,
        depthwise=False,
        attn='ese',
    ),
    ese_vovnet39b=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 1, 2, 2],
        residual=True,
        depthwise=False,
        attn='ese',
    ),
    ese_vovnet57b=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 1, 4, 3],
        residual=True,
        depthwise=False,
        attn='ese',
    ),
    ese_vovnet99b=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 3, 9, 3],
        residual=True,
        depthwise=False,
        attn='ese',
    ),
    eca_vovnet39b=dict(
        stem_chs=[64, 64, 128],
        stage_conv_chs=[128, 160, 192, 224],
        stage_out_chs=[256, 512, 768, 1024],
        layer_per_block=5,
        block_per_stage=[1, 1, 2, 2],
        residual=True,
        depthwise=False,
        attn='eca',
    ),
)
model_cfgs['ese_vovnet39b_evos'] = model_cfgs['ese_vovnet39b']


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    return convert_torch_state_dict(state_dict, model)


def _create_vovnet(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        VovNet, variant, pretrained,
        model_cfg=model_cfgs[variant],
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3)),
        **kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.0.conv', 'classifier': 'head.fc',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'vovnet39a.untrained': _cfg(),
    'vovnet57a.untrained': _cfg(),
    'ese_vovnet19b_slim_dw.untrained': _cfg(),
    'ese_vovnet19b_dw.ra_in1k': _cfg(
        hf_hub_id='timm/', test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'ese_vovnet19b_slim.untrained': _cfg(),
    'ese_vovnet19b.untrained': _cfg(),
    'ese_vovnet39b.ra_in1k': _cfg(
        hf_hub_id='timm/', test_input_size=(3, 288, 288), test_crop_pct=0.95),
    'ese_vovnet57b.ra4_e3600_r256_in1k': _cfg(
        hf_hub_id='timm/', mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5),
        input_size=(3, 256, 256), crop_pct=0.95, test_input_size=(3, 320, 320), test_crop_pct=1.0),
    'ese_vovnet99b.untrained': _cfg(),
    'eca_vovnet39b.untrained': _cfg(),
    'ese_vovnet39b_evos.untrained': _cfg(),
})


@register_model
def vovnet39a(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('vovnet39a', pretrained=pretrained, **kwargs)


@register_model
def vovnet57a(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('vovnet57a', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet19b_slim_dw(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet19b_slim_dw', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet19b_dw(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet19b_dw', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet19b_slim(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet19b_slim', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet19b(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet19b', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet39b(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet39b', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet57b(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet57b', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet99b(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('ese_vovnet99b', pretrained=pretrained, **kwargs)


@register_model
def eca_vovnet39b(pretrained=False, **kwargs) -> VovNet:
    return _create_vovnet('eca_vovnet39b', pretrained=pretrained, **kwargs)


@register_model
def ese_vovnet39b_evos(pretrained=False, **kwargs) -> VovNet:
    """V2 w/ EvoNorm (reference vovnet.py:556-559)."""
    def norm_act_fn(num_features, apply_act=True, act_layer=None, **nkwargs):
        from ..layers import EvoNorm2dS0
        return EvoNorm2dS0(num_features, apply_act=apply_act, **nkwargs)
    return _create_vovnet('ese_vovnet39b_evos', pretrained=pretrained, norm_layer=norm_act_fn, **kwargs)
