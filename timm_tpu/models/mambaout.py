"""MambaOut — gated CNN blocks, "do we need mamba for vision?" (NHWC / nnx).

Re-implements reference timm/models/mambaout.py:1-737 (MambaOut): a
channels-last four-stage net of Gated CNN blocks (the MetaFormer/Mamba token
mixer with the SSM removed): LN → fc1 → split(gate, identity, conv) → dw conv
on the conv split → gate * concat → fc2, plus an unusual MLP classifier head
(norm → fc → act → norm → fc).

TPU notes: the reference is already channels-last internally and permutes
around every conv; here the whole net is NHWC so only the gated split/concat
remains — XLA fuses the gate multiply into the fc2 matmul's prologue. The
partial-channel dw conv is a static slice.
"""
from functools import partial
from typing import Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import (
    ClNormMlpClassifierHead, Dropout, DropPath, LayerNorm, LayerScale,
    calculate_drop_path_rates, get_act_fn, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._manipulate import (
    BlockStackError, resolve_stage_scan, scan_stage_stack, warn_scan_fallback,
)
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['MambaOut']


def _conv(in_c, out_c, k, s=1, p=0, groups=1, *, dtype, param_dtype, rngs):
    return nnx.Conv(
        in_c, out_c, kernel_size=(k, k), strides=s, padding=[(p, p), (p, p)],
        feature_group_count=groups, use_bias=True,
        kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
        dtype=dtype, param_dtype=param_dtype, rngs=rngs)


def _linear(in_f, out_f, bias=True, *, dtype, param_dtype, rngs):
    return nnx.Linear(in_f, out_f, use_bias=bias, kernel_init=trunc_normal_(std=0.02),
                      bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)


class Stem(nnx.Module):
    """Two strided 3x3 convs with LN(s) (reference mambaout.py:22-69)."""

    def __init__(self, in_chs=3, out_chs=96, mid_norm=True, act_layer='gelu',
                 norm_layer=LayerNorm, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = _conv(in_chs, out_chs // 2, 3, 2, 1, **kw)
        self.norm1 = norm_layer(out_chs // 2, rngs=rngs) if mid_norm else None
        self.act = get_act_fn(act_layer)
        self.conv2 = _conv(out_chs // 2, out_chs, 3, 2, 1, **kw)
        self.norm2 = norm_layer(out_chs, rngs=rngs)

    def __call__(self, x):
        x = self.conv1(x)
        if self.norm1 is not None:
            x = self.norm1(x)
        x = self.act(x)
        return self.norm2(self.conv2(x))


class DownsampleNormFirst(nnx.Module):
    """LN → strided conv (reference mambaout.py:72-99)."""

    def __init__(self, in_chs=96, out_chs=198, norm_layer=LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.norm = norm_layer(in_chs, rngs=rngs)
        self.conv = _conv(in_chs, out_chs, 3, 2, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        return self.conv(self.norm(x))


class Downsample(nnx.Module):
    """Strided conv → LN (reference mambaout.py:102-129)."""

    def __init__(self, in_chs=96, out_chs=198, norm_layer=LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = _conv(in_chs, out_chs, 3, 2, 1, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(out_chs, rngs=rngs)

    def __call__(self, x):
        return self.norm(self.conv(x))


class _FcActNorm(nnx.Module):
    """fc → act → norm pre-logits (keys pre_logits.fc/.norm)."""

    def __init__(self, in_features, hidden_size, act_layer='gelu', norm_layer=LayerNorm,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.fc = _linear(in_features, hidden_size, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.norm = norm_layer(hidden_size, rngs=rngs)

    def __call__(self, x):
        return self.norm(self.act(self.fc(x)))


class MlpHead(nnx.Module):
    """MambaOut's norm → fc → act → norm → fc head (reference mambaout.py:132-193)."""

    def __init__(self, in_features, num_classes=1000, pool_type='avg', act_layer='gelu',
                 mlp_ratio: Optional[int] = 4, norm_layer=LayerNorm, drop_rate=0., bias=True,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        hidden_size = int(mlp_ratio * in_features) if mlp_ratio is not None else None
        self.pool_type = pool_type
        self.in_features = in_features
        self.num_features = hidden_size or in_features
        self._dd = dict(dtype=dtype, param_dtype=param_dtype)

        self.norm = norm_layer(in_features, rngs=rngs)
        self.pre_logits = _FcActNorm(in_features, hidden_size, act_layer, norm_layer, **kw) \
            if hidden_size else None
        self.fc = _linear(self.num_features, num_classes, bias=bias, **kw) if num_classes > 0 else None
        self.head_dropout = Dropout(drop_rate, rngs=rngs)

    def reset(self, num_classes: int, pool_type: Optional[str] = None,
              reset_other: bool = False, *, rngs=None):
        if pool_type is not None:
            self.pool_type = pool_type
        if reset_other:
            self.norm = None
            self.pre_logits = None
            self.num_features = self.in_features
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.fc = _linear(self.num_features, num_classes, rngs=rngs, **self._dd) \
            if num_classes > 0 else None

    def __call__(self, x, pre_logits: bool = False):
        if self.pool_type == 'avg':
            x = x.mean(axis=(1, 2))
        if self.norm is not None:
            x = self.norm(x)
        if self.pre_logits is not None:
            x = self.pre_logits(x)
        x = self.head_dropout(x)
        if pre_logits or self.fc is None:
            return x
        return self.fc(x)


class GatedConvBlock(nnx.Module):
    """Gated CNN block: LN → fc1 → (gate | id | dw-conv split) → fc2
    (reference mambaout.py:195-249). The conv runs on a static channel slice."""

    def __init__(self, dim, expansion_ratio=8 / 3, kernel_size=7, conv_ratio=1.0,
                 ls_init_value=None, norm_layer=LayerNorm, act_layer='gelu', drop_path=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs, **kwargs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs)
        hidden = int(expansion_ratio * dim)
        self.fc1 = _linear(dim, hidden * 2, **kw)
        self.act = get_act_fn(act_layer)
        conv_channels = int(conv_ratio * dim)
        self.split_indices = (hidden, hidden - conv_channels, conv_channels)
        self.conv = _conv(conv_channels, conv_channels, kernel_size, 1, kernel_size // 2,
                          groups=conv_channels, **kw)
        self.fc2 = _linear(hidden, dim, **kw)
        self.ls = LayerScale(dim, ls_init_value, param_dtype=param_dtype, rngs=rngs) \
            if ls_init_value is not None else None
        self.drop_path = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x):
        shortcut = x  # (B, H, W, C)
        x = self.fc1(self.norm(x))
        g_end, i_end = self.split_indices[0], self.split_indices[0] + self.split_indices[1]
        g, i, c = x[..., :g_end], x[..., g_end:i_end], x[..., i_end:]
        c = self.conv(c)
        x = self.fc2(self.act(g) * jnp.concatenate([i, c], axis=-1))
        if self.ls is not None:
            x = self.ls(x)
        if self.drop_path is not None:
            x = self.drop_path(x)
        return x + shortcut


class MambaOutStage(nnx.Module):
    """Optional downsample + gated conv blocks (reference mambaout.py:252-305)."""

    def __init__(self, dim, dim_out=None, depth=4, expansion_ratio=8 / 3, kernel_size=7,
                 conv_ratio=1.0, downsample='', ls_init_value=None, norm_layer=LayerNorm,
                 act_layer='gelu', drop_path=0.,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        dim_out = dim_out or dim
        self.grad_checkpointing = False
        self.stage_scan = False
        if downsample == 'conv':
            self.downsample = Downsample(dim, dim_out, norm_layer=norm_layer, **kw)
        elif downsample == 'conv_nf':
            self.downsample = DownsampleNormFirst(dim, dim_out, norm_layer=norm_layer, **kw)
        else:
            assert dim == dim_out
            self.downsample = None
        self.blocks = nnx.List([
            GatedConvBlock(
                dim=dim_out, expansion_ratio=expansion_ratio, kernel_size=kernel_size,
                conv_ratio=conv_ratio, ls_init_value=ls_init_value, norm_layer=norm_layer,
                act_layer=act_layer,
                drop_path=drop_path[j] if isinstance(drop_path, (list, tuple)) else drop_path,
                **kw)
            for j in range(depth)])

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        if self.stage_scan:
            try:
                return scan_stage_stack(self.blocks, x, remat=self.grad_checkpointing)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e, what='stage_scan')
        remat_blk = nnx.remat(GatedConvBlock.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            x = remat_blk(blk, x) if remat_blk is not None else blk(x)
        return x


class MambaOut(nnx.Module):
    """MambaOut (reference mambaout.py:307-527)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            depths: Tuple[int, ...] = (3, 3, 9, 3),
            dims: Tuple[int, ...] = (96, 192, 384, 576),
            norm_layer=LayerNorm,
            act_layer='gelu',
            conv_ratio: float = 1.0,
            expansion_ratio: float = 8 / 3,
            kernel_size: int = 7,
            stem_mid_norm: bool = True,
            ls_init_value: Optional[float] = None,
            downsample: str = 'conv',
            drop_path_rate: float = 0.,
            drop_rate: float = 0.,
            head_fn: str = 'default',
            stage_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.output_fmt = 'NHWC'
        if not isinstance(depths, (list, tuple)):
            depths = (depths,)
        if not isinstance(dims, (list, tuple)):
            dims = (dims,)

        num_stage = len(depths)
        self.num_stage = num_stage
        self.feature_info = []

        self.stem = Stem(in_chans, dims[0], mid_norm=stem_mid_norm,
                         act_layer=act_layer, norm_layer=norm_layer, **kw)
        prev_dim = dims[0]
        dp_rates = calculate_drop_path_rates(drop_path_rate, depths, stagewise=True)
        stages = []
        curr_stride = 4
        for i in range(num_stage):
            dim = dims[i]
            stride = 2 if curr_stride == 2 or i > 0 else 1
            curr_stride *= stride
            stages.append(MambaOutStage(
                dim=prev_dim, dim_out=dim, depth=depths[i], kernel_size=kernel_size,
                conv_ratio=conv_ratio, expansion_ratio=expansion_ratio,
                downsample=downsample if i > 0 else '',
                ls_init_value=ls_init_value, norm_layer=norm_layer, act_layer=act_layer,
                drop_path=dp_rates[i], **kw))
            prev_dim = dim
            self.feature_info += [dict(num_chs=prev_dim, reduction=curr_stride, module=f'stages.{i}')]
        self.stages = nnx.List(stages)
        self.set_stage_scan(resolve_stage_scan(stage_scan))

        if head_fn == 'default':
            # unusual norm → pool → fc → act → norm → fc combo
            self.head = MlpHead(
                prev_dim, num_classes, pool_type=global_pool, drop_rate=drop_rate,
                norm_layer=norm_layer, **kw)
        else:
            self.head = ClNormMlpClassifierHead(
                prev_dim, num_classes, hidden_size=int(prev_dim * 4), pool_type=global_pool,
                norm_layer=norm_layer, drop_rate=drop_rate, **kw)
        self.num_features = prev_dim
        self.head_hidden_size = self.head.num_features

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=r'^stages\.(\d+)' if coarse else [
                (r'^stages\.(\d+)\.downsample', (0,)),
                (r'^stages\.(\d+)\.blocks\.(\d+)', None),
            ])

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def set_stage_scan(self, enable: bool = True):
        for s in self.stages:
            s.stage_scan = enable

    # stage scan IS this family's scan-over-layers: generic machinery that
    # toggles `set_block_scan` (bench replay, probes) reaches it too
    set_block_scan = set_stage_scan

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.stem(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        intermediates = []
        x = self.stem(x)
        stages = self.stages if not stop_early else self.stages[:max_index + 1]
        for feat_idx, stage in enumerate(stages):
            x = stage(x)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    if 'stem.conv1.weight' not in state_dict and any(k.startswith('downsample_layers') for k in state_dict):
        # original (non-timm) checkpoint layout (reference mambaout.py:529-551)
        import re
        out = {}
        for k, v in state_dict.items():
            k = k.replace('downsample_layers.0.', 'stem.')
            k = re.sub(r'stages.([0-9]+).([0-9]+)', r'stages.\1.blocks.\2', k)
            k = re.sub(r'downsample_layers.([0-9]+)', r'stages.\1.downsample', k)
            if k.startswith('norm.'):
                k = k.replace('norm.', 'head.norm.')
            elif k.startswith('head.'):
                k = k.replace('head.fc1.', 'head.pre_logits.fc.')
                k = k.replace('head.norm.', 'head.pre_logits.norm.')
                k = k.replace('head.fc2.', 'head.fc.')
            out[k] = v
        state_dict = out
    return convert_torch_state_dict(state_dict, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'test_input_size': (3, 288, 288),
        'pool_size': (7, 7), 'crop_pct': 1.0, 'interpolation': 'bicubic',
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'first_conv': 'stem.conv1', 'classifier': 'head.fc',
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'mambaout_femto.in1k': _cfg(),
    'mambaout_kobe.in1k': _cfg(),
    'mambaout_tiny.in1k': _cfg(),
    'mambaout_small.in1k': _cfg(),
    'mambaout_base.in1k': _cfg(),
    'mambaout_small_rw.sw_e450_in1k': _cfg(),
    'mambaout_base_short_rw.sw_e500_in1k': _cfg(crop_pct=0.95, test_crop_pct=1.0),
    'mambaout_base_tall_rw.sw_e500_in1k': _cfg(crop_pct=0.95, test_crop_pct=1.0),
    'mambaout_base_wide_rw.sw_e500_in1k': _cfg(crop_pct=0.95, test_crop_pct=1.0),
    'mambaout_base_plus_rw.sw_e150_in12k_ft_in1k': _cfg(),
    'test_mambaout': _cfg(input_size=(3, 160, 160), test_input_size=(3, 192, 192), pool_size=(5, 5)),
})


def _create_mambaout(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        MambaOut, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3), feature_cls='getter'),
        **kwargs,
    )


@register_model
def mambaout_femto(pretrained=False, **kwargs):
    model_args = dict(depths=(3, 3, 9, 3), dims=(48, 96, 192, 288))
    return _create_mambaout('mambaout_femto', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_kobe(pretrained=False, **kwargs):
    """Kobe Memorial Version with 24 Gated CNN blocks."""
    model_args = dict(depths=(3, 3, 15, 3), dims=(48, 96, 192, 288))
    return _create_mambaout('mambaout_kobe', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_tiny(pretrained=False, **kwargs):
    model_args = dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 576))
    return _create_mambaout('mambaout_tiny', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_small(pretrained=False, **kwargs):
    model_args = dict(depths=(3, 4, 27, 3), dims=(96, 192, 384, 576))
    return _create_mambaout('mambaout_small', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_base(pretrained=False, **kwargs):
    model_args = dict(depths=(3, 4, 27, 3), dims=(128, 256, 512, 768))
    return _create_mambaout('mambaout_base', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_small_rw(pretrained=False, **kwargs):
    model_args = dict(
        depths=(3, 4, 27, 3), dims=(96, 192, 384, 576), stem_mid_norm=False,
        downsample='conv_nf', ls_init_value=1e-6, head_fn='norm_mlp')
    return _create_mambaout('mambaout_small_rw', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_base_short_rw(pretrained=False, **kwargs):
    model_args = dict(
        depths=(3, 3, 25, 3), dims=(128, 256, 512, 768), expansion_ratio=3.0, conv_ratio=1.25,
        stem_mid_norm=False, downsample='conv_nf', ls_init_value=1e-6, head_fn='norm_mlp')
    return _create_mambaout('mambaout_base_short_rw', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_base_tall_rw(pretrained=False, **kwargs):
    model_args = dict(
        depths=(3, 4, 30, 3), dims=(128, 256, 512, 768), expansion_ratio=2.5, conv_ratio=1.25,
        stem_mid_norm=False, downsample='conv_nf', ls_init_value=1e-6, head_fn='norm_mlp')
    return _create_mambaout('mambaout_base_tall_rw', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_base_wide_rw(pretrained=False, **kwargs):
    model_args = dict(
        depths=(3, 4, 27, 3), dims=(128, 256, 512, 768), expansion_ratio=3.0, conv_ratio=1.5,
        stem_mid_norm=False, downsample='conv_nf', ls_init_value=1e-6, act_layer='silu',
        head_fn='norm_mlp')
    return _create_mambaout('mambaout_base_wide_rw', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def mambaout_base_plus_rw(pretrained=False, **kwargs):
    model_args = dict(
        depths=(3, 4, 30, 3), dims=(128, 256, 512, 768), expansion_ratio=3.0, conv_ratio=1.5,
        stem_mid_norm=False, downsample='conv_nf', ls_init_value=1e-6, act_layer='silu',
        head_fn='norm_mlp')
    return _create_mambaout('mambaout_base_plus_rw', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_mambaout(pretrained=False, **kwargs):
    model_args = dict(
        depths=(1, 1, 3, 1), dims=(16, 32, 48, 64), expansion_ratio=3, stem_mid_norm=False,
        downsample='conv_nf', ls_init_value=1e-4, act_layer='silu', head_fn='norm_mlp')
    return _create_mambaout('test_mambaout', pretrained=pretrained, **dict(model_args, **kwargs))
