"""Pretrained weight/config metadata (reference: timm/models/_pretrained.py:11-94)."""
from __future__ import annotations

from copy import deepcopy
from dataclasses import dataclass, field, asdict, replace
from typing import Any, Deque, Dict, Optional, Tuple, Union

__all__ = ['PretrainedCfg', 'DefaultCfg', 'filter_pretrained_cfg']


@dataclass
class PretrainedCfg:
    """Describes a pretrained weight source + input/preproc metadata."""
    # weight source
    url: Optional[Union[str, Tuple[str, str]]] = None
    file: Optional[str] = None
    state_dict: Optional[Dict[str, Any]] = None
    hf_hub_id: Optional[str] = None
    hf_hub_filename: Optional[str] = None

    source: Optional[str] = None
    architecture: Optional[str] = None
    tag: Optional[str] = None
    custom_load: bool = False

    # input / data config
    input_size: Tuple[int, int, int] = (3, 224, 224)
    test_input_size: Optional[Tuple[int, int, int]] = None
    min_input_size: Optional[Tuple[int, int, int]] = None
    fixed_input_size: bool = False
    interpolation: str = 'bicubic'
    crop_pct: float = 0.875
    test_crop_pct: Optional[float] = None
    crop_mode: str = 'center'
    mean: Tuple[float, ...] = (0.485, 0.456, 0.406)
    std: Tuple[float, ...] = (0.229, 0.224, 0.225)

    # head / arch metadata
    num_classes: int = 1000
    label_offset: Optional[int] = None
    label_names: Optional[Tuple[str]] = None
    label_descriptions: Optional[Dict[str, str]] = None
    pool_size: Optional[Tuple[int, ...]] = None
    test_pool_size: Optional[Tuple[int, ...]] = None
    first_conv: Optional[Union[str, Tuple[str, ...]]] = None
    classifier: Optional[Union[str, Tuple[str, ...]]] = None

    license: Optional[str] = None
    description: Optional[str] = None
    origin_url: Optional[str] = None
    paper_name: Optional[str] = None
    paper_ids: Optional[Union[str, Tuple[str]]] = None
    notes: Optional[Tuple[str]] = None

    @property
    def has_weights(self) -> bool:
        return bool(self.url or self.file or self.hf_hub_id or self.state_dict is not None)

    def to_dict(self, remove_source: bool = False, remove_null: bool = True) -> Dict[str, Any]:
        return filter_pretrained_cfg(asdict(self), remove_source=remove_source, remove_null=remove_null)


def filter_pretrained_cfg(cfg: Dict[str, Any], remove_source: bool = False, remove_null: bool = True):
    filtered = {}
    keep_null = {'pool_size', 'first_conv', 'classifier'}
    for k, v in cfg.items():
        if remove_source and k in {'url', 'file', 'hf_hub_id', 'hf_hub_filename', 'state_dict'}:
            continue
        if remove_null and v is None and k not in keep_null:
            continue
        filtered[k] = v
    return filtered


@dataclass
class DefaultCfg:
    """Tag-priority container; first tag is the default (reference _pretrained.py:81)."""
    tags: list = field(default_factory=list)
    cfgs: Dict[str, PretrainedCfg] = field(default_factory=dict)
    is_pretrained: bool = False

    @property
    def default(self) -> PretrainedCfg:
        return self.cfgs[self.tags[0]]

    @property
    def default_with_tag(self) -> Tuple[str, PretrainedCfg]:
        tag = self.tags[0]
        return tag, self.cfgs[tag]
