"""Multi-scale feature extraction (reference: timm/models/_features.py).

Functional JAX has no forward hooks; the primary mechanism is the model's
`forward_intermediates()` method (reference `FeatureGetterNet` style,
_features.py:435-482). `features_only=True` wraps models in FeatureGetterNet.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from flax import nnx

__all__ = ['FeatureInfo', 'FeatureGetterNet', 'feature_take_indices']


def feature_take_indices(
        num_features: int,
        indices: Optional[Union[int, List[int], Tuple[int, ...]]] = None,
        as_set: bool = False,
):
    """Resolve relative/negative indices → absolute (reference _features.py:28)."""
    if indices is None:
        indices = num_features
    if isinstance(indices, int):
        # from the end
        take_indices = [num_features - indices + i for i in range(indices)]
    else:
        take_indices = [num_features + i if i < 0 else i for i in indices]
    for i in take_indices:
        assert 0 <= i < num_features, f'feature index {i} out of range [0, {num_features})'
    max_index = max(take_indices)
    return (set(take_indices) if as_set else take_indices), max_index


class FeatureInfo:
    def __init__(self, feature_info: List[Dict], out_indices: Tuple[int, ...]):
        prev_reduction = 1
        for i, fi in enumerate(feature_info):
            assert 'num_chs' in fi and fi['num_chs'] > 0
            assert 'reduction' in fi and fi['reduction'] >= prev_reduction
            prev_reduction = fi['reduction']
            fi.setdefault('module', f'layer_{i}')
            fi.setdefault('index', i)
        self.out_indices = out_indices
        self.info = feature_info

    def from_other(self, out_indices: Tuple[int, ...]):
        import copy
        return FeatureInfo(copy.deepcopy(self.info), out_indices)

    def get(self, key: str, idx: Optional[Union[int, tuple]] = None):
        if idx is None:
            return [self.info[i][key] for i in self.out_indices]
        if isinstance(idx, (tuple, list)):
            return [self.info[i][key] for i in idx]
        return self.info[idx][key]

    def get_dicts(self, keys=None, idx=None):
        if idx is None:
            idx = self.out_indices
        if isinstance(idx, int):
            idx = [idx]
        if keys is None:
            return [self.info[i] for i in idx]
        return [{k: self.info[i][k] for k in keys} for i in idx]

    def channels(self, idx=None):
        return self.get('num_chs', idx)

    def reduction(self, idx=None):
        return self.get('reduction', idx)

    def module_name(self, idx=None):
        return self.get('module', idx)

    def __getitem__(self, item):
        return self.info[item]

    def __len__(self):
        return len(self.info)


class FeatureGetterNet(nnx.Module):
    """`features_only` wrapper driving model.forward_intermediates
    (reference _features.py:435)."""

    def __init__(
            self,
            model: nnx.Module,
            out_indices=4,
            out_map=None,
            return_dict: bool = False,
            output_fmt: str = 'NHWC',
            norm: bool = False,
            prune: bool = True,
            **kwargs,
    ):
        if prune and hasattr(model, 'prune_intermediate_layers'):
            out_indices = model.prune_intermediate_layers(out_indices, prune_norm=not norm)
        self.feature_info = _build_feature_info(model, out_indices)
        self.model = model
        self.out_indices = out_indices
        self.out_map = out_map
        self.return_dict = return_dict
        self.output_fmt = output_fmt
        self.norm = norm

    def __call__(self, x):
        features = self.model.forward_intermediates(
            x,
            indices=self.out_indices,
            norm=self.norm,
            output_fmt=self.output_fmt,
            intermediates_only=True,
        )
        if self.return_dict:
            names = self.out_map or [f'layer_{i}' for i in range(len(features))]
            return dict(zip(names, features))
        return features


def _build_feature_info(model, out_indices):
    raw = getattr(model, 'feature_info', None)
    if raw is None:
        return None
    if isinstance(raw, FeatureInfo):
        take, _ = feature_take_indices(len(raw), out_indices)
        return raw.from_other(tuple(take))
    import copy
    info = copy.deepcopy(raw)
    take, _ = feature_take_indices(len(info), out_indices)
    return FeatureInfo(info, tuple(take))
