"""EVA / EVA02 (reference: timm/models/eva.py:1-3096), TPU-native.

ViT with rotary position embeddings (shared per-model ROPE table, applied to
non-prefix tokens), optional SwiGLU MLP with inner norm, and pre/post-norm
block options. Covers the eva02 family (the reference zoo's top-1 leader).
"""
from __future__ import annotations

from functools import partial

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    AttentionPoolLatent, AttentionRope, Dropout, DropPath, GluMlp, LayerNorm,
    LayerScale, Mlp, PatchEmbed, RotaryEmbeddingCat, SwiGLU,
    calculate_drop_path_rates, create_rope_embed, get_norm_layer,
    global_pool_nlc, resample_abs_pos_embed, to_2tuple, trunc_normal_, zeros_,
)
from ..layers.drop import apply_drop_path
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, drop_path_scan_inputs, resolve_block_scan,
    scan_block_stack, warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['Eva', 'EvaBlock', 'EvaAttention']


class EvaAttention(nnx.Module):
    """ROPE attention with optional unfused q/k/v projections — eva02
    base/large checkpoints store separate q/k/v with no k bias
    (reference eva.py EvaAttention)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            scale_norm: bool = False,
            rotate_half: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        from functools import partial as _partial
        from ..layers.attention import scaled_dot_product_attention, apply_rot_embed_cat
        from ..layers.drop import Dropout as _Dropout, dropout_rng_key as _drk
        assert dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.attn_drop_rate = attn_drop
        self.qkv_fused = qkv_fused
        self.rotate_half = rotate_half
        self._sdpa = scaled_dot_product_attention
        self._rot = apply_rot_embed_cat
        self._drk = _drk

        linear = _partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        if qkv_fused:
            # reference layout: unbiased fused projection + separate q/v bias
            # params (k bias fixed at zero) — BEiT-style (reference eva.py:161)
            self.qkv = linear(dim, dim * 3, use_bias=False)
            self.q_proj = self.k_proj = self.v_proj = None
            if qkv_bias:
                self.q_bias = nnx.Param(jnp.zeros((dim,), param_dtype))
                self.v_bias = nnx.Param(jnp.zeros((dim,), param_dtype))
            else:
                self.q_bias = self.v_bias = None
        else:
            self.qkv = None
            self.q_bias = self.v_bias = None
            self.q_proj = linear(dim, dim, use_bias=qkv_bias)
            self.k_proj = linear(dim, dim, use_bias=False)
            self.v_proj = linear(dim, dim, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = _Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs) if scale_norm else None
        self.proj = linear(dim, dim)
        self.proj_drop = _Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, rope=None, attn_mask=None):
        B, N, C = x.shape
        if self.qkv_fused:
            qkv = self.qkv(x)
            if self.q_bias is not None:
                bias = jnp.concatenate([
                    self.q_bias[...], jnp.zeros_like(self.q_bias[...]), self.v_bias[...]])
                qkv = qkv + bias.astype(qkv.dtype)
            qkv = qkv.reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            q = self.q_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            k = self.k_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            v = self.v_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        from ..parallel import shard_activation
        q, k, v = (shard_activation(t, 'heads') for t in (q, k, v))
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        if rope is not None:
            num_prefix = N - rope.shape[-2]
            half = self.rotate_half
            if num_prefix > 0:
                q = jnp.concatenate(
                    [q[..., :num_prefix, :], self._rot(q[..., num_prefix:, :], rope, half=half)], axis=-2)
                k = jnp.concatenate(
                    [k[..., :num_prefix, :], self._rot(k[..., num_prefix:, :], rope, half=half)], axis=-2)
            else:
                q, k = self._rot(q, rope, half=half), self._rot(k, rope, half=half)
            q = q.astype(v.dtype)
            k = k.astype(v.dtype)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = self._drk(self.attn_drop) if dropout_p > 0.0 else None
        x = self._sdpa(q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
                       dropout_key=dropout_key, scale=self.scale)
        x = shard_activation(x.transpose(0, 2, 1, 3).reshape(B, N, C), 'hidden')
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        return self.proj_drop(x)


class EvaBlock(nnx.Module):
    def __init__(
            self,
            dim: int,
            num_heads: int,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            mlp_ratio: float = 4.0,
            swiglu_mlp: bool = False,
            scale_mlp: bool = False,
            scale_attn_inner: bool = False,
            attn_type: str = 'eva',
            rotate_half: bool = False,
            num_prefix_tokens: int = 1,
            swiglu_align_to: int = 0,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: float = 0.0,
            init_values: Optional[float] = None,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            use_post_norm: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # post-norm (beit3-style, reference eva.py EvaBlockPostNorm:430-525):
        # residual adds norm(branch(x)) and layer scale is ignored
        self.use_post_norm = use_post_norm
        if use_post_norm:
            init_values = None
        self.norm1 = norm_layer(dim, rngs=rngs)
        if attn_type == 'rope':
            # plain fused/unfused rope attention (PE / naver rope-vit,
            # reference eva.py:327,460 attn_cls selection)
            self.attn = AttentionRope(
                dim,
                num_heads=num_heads,
                qkv_bias=qkv_bias,
                qkv_fused=qkv_fused,
                qk_norm=qk_norm,
                scale_norm=scale_attn_inner,
                num_prefix_tokens=num_prefix_tokens,
                rotate_half=rotate_half,
                attn_drop=attn_drop,
                proj_drop=proj_drop,
                norm_layer=norm_layer,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
        else:
            self.attn = EvaAttention(
                dim,
                num_heads=num_heads,
                qkv_bias=qkv_bias,
                qkv_fused=qkv_fused,
                qk_norm=qk_norm,
                attn_drop=attn_drop,
                proj_drop=proj_drop,
                norm_layer=norm_layer,
                scale_norm=scale_attn_inner,
                rotate_half=rotate_half,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
        self.ls1 = LayerScale(dim, init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        hidden = int(dim * mlp_ratio)
        if swiglu_mlp:
            if scale_mlp or swiglu_align_to:
                # norm/alignment requires the un-packed variant (reference eva.py block init)
                self.mlp = SwiGLU(
                    dim, hidden, norm_layer=norm_layer if scale_mlp else None,
                    align_to=swiglu_align_to,
                    drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            else:
                # packed weights (one fc1) to match eva02 tiny/small checkpoints
                self.mlp = GluMlp(
                    dim, hidden * 2, act_layer='silu', gate_last=False,
                    drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.mlp = Mlp(
                dim, hidden, act_layer=act_layer,
                norm_layer=norm_layer if scale_mlp else None,
                drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.ls2 = LayerScale(dim, init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, rope=None, attn_mask=None, drop_path_override=None):
        if self.use_post_norm:
            x = x + apply_drop_path(
                self.norm1(self.attn(x, rope=rope, attn_mask=attn_mask)),
                self.drop_path1, drop_path_override, 0)
            x = x + apply_drop_path(
                self.norm2(self.mlp(x)), self.drop_path2, drop_path_override, 1)
            return x
        y = self.attn(self.norm1(x), rope=rope, attn_mask=attn_mask)
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + apply_drop_path(y, self.drop_path1, drop_path_override, 0)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + apply_drop_path(y, self.drop_path2, drop_path_override, 1)
        return x


class Eva(nnx.Module):
    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: int = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            mlp_ratio: float = 4.0,
            swiglu_mlp: bool = False,
            scale_mlp: bool = False,
            scale_attn_inner: bool = False,
            swiglu_align_to: int = 0,
            attn_type: str = 'eva',
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            init_values: Optional[float] = None,
            class_token: bool = True,
            num_reg_tokens: int = 0,
            no_embed_class: bool = False,
            use_abs_pos_emb: bool = True,
            use_rot_pos_emb: bool = False,
            rope_type: Optional[str] = 'cat',
            ref_feat_shape: Optional[Tuple[int, int]] = None,
            rope_grid_offset: float = 0.0,
            rope_grid_indexing: str = 'ij',
            rope_temperature: float = 10000.0,
            rope_rotate_half: bool = False,
            use_post_norm: bool = False,
            use_pre_transformer_norm: bool = False,
            use_post_transformer_norm: Optional[bool] = None,
            use_fc_norm: Optional[bool] = None,
            attn_pool_num_heads: Optional[int] = None,
            attn_pool_mlp_ratio: Optional[float] = None,
            dynamic_img_size: bool = False,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Union[str, Callable] = 'gelu',
            block_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert global_pool in ('', 'avg', 'avgmax', 'max', 'token', 'map')
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.num_prefix_tokens = (1 if class_token else 0) + num_reg_tokens
        self.num_reg_tokens = num_reg_tokens
        self.no_embed_class = no_embed_class
        self.dynamic_img_size = dynamic_img_size
        self.grad_checkpointing = False
        self.block_scan = resolve_block_scan(block_scan)

        # norm / pool placement (reference eva.py:643-651)
        activate_pre_norm = use_pre_transformer_norm
        activate_fc_norm = use_fc_norm if use_fc_norm is not None else global_pool == 'avg'
        activate_post_norm = use_post_transformer_norm if use_post_transformer_norm is not None \
            else not activate_fc_norm

        embed_args = {}
        if dynamic_img_size:
            embed_args.update(dict(strict_img_size=False))
        self.patch_embed = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans, embed_dim=embed_dim,
            bias=not use_pre_transformer_norm,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs, **embed_args)
        num_patches = self.patch_embed.num_patches

        self.cls_token = nnx.Param(jnp.zeros((1, 1, embed_dim), param_dtype)) if class_token else None
        self.reg_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, num_reg_tokens, embed_dim), param_dtype)) \
            if num_reg_tokens else None

        num_pos_tokens = num_patches if no_embed_class else num_patches + self.num_prefix_tokens
        if use_abs_pos_emb:
            self.pos_embed = nnx.Param(trunc_normal_(std=0.02)(
                rngs.params(), (1, num_pos_tokens, embed_dim), param_dtype))
        else:
            self.pos_embed = None
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        self.rope_mixed = False
        if use_rot_pos_emb:
            ref_feat_shape = to_2tuple(ref_feat_shape) if ref_feat_shape is not None else None
            rope_kwargs = dict(
                dim=embed_dim,
                num_heads=num_heads,
                feat_shape=None if dynamic_img_size else self.patch_embed.grid_size,
                temperature=rope_temperature,
                grid_indexing=rope_grid_indexing,
            )
            if rope_type == 'mixed':
                rope_kwargs.update(dict(depth=depth))
                self.rope_mixed = True
            elif rope_type == 'cat':
                rope_kwargs.update(dict(
                    in_pixels=False,
                    grid_offset=rope_grid_offset,
                    ref_feat_shape=ref_feat_shape,
                ))
            elif rope_type == 'dinov3':
                rope_kwargs.update(dict(rotate_half=rope_rotate_half))
            self.rope = create_rope_embed(rope_type=rope_type, rngs=rngs, **rope_kwargs)
        else:
            self.rope = None

        self.norm_pre = norm_layer(embed_dim, rngs=rngs) if activate_pre_norm else None

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            EvaBlock(
                dim=embed_dim,
                num_heads=num_heads,
                qkv_bias=qkv_bias,
                qkv_fused=qkv_fused,
                qk_norm=qk_norm,
                mlp_ratio=mlp_ratio,
                swiglu_mlp=swiglu_mlp,
                scale_mlp=scale_mlp,
                scale_attn_inner=scale_attn_inner,
                swiglu_align_to=swiglu_align_to,
                attn_type=attn_type,
                rotate_half=rope_rotate_half,
                num_prefix_tokens=self.num_prefix_tokens,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                init_values=init_values,
                act_layer=act_layer,
                norm_layer=norm_layer,
                use_post_norm=use_post_norm,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        reduction = self.patch_embed.patch_size[0]
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=reduction) for i in range(depth)]

        self.norm = norm_layer(embed_dim, rngs=rngs) if activate_post_norm else None
        if global_pool == 'map':
            self.attn_pool = AttentionPoolLatent(
                embed_dim,
                num_heads=attn_pool_num_heads or num_heads,
                mlp_ratio=attn_pool_mlp_ratio or mlp_ratio,
                norm_layer=norm_layer,
                act_layer='gelu',
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
        else:
            self.attn_pool = None
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if activate_fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'reg_token'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|reg_token',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm|^fc_norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def set_block_scan(self, enable: bool = True):
        """Toggle scan-over-layers block execution (see VisionTransformer).
        Mixed-rope models thread their per-depth rope table through the scan."""
        self.block_scan = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _pos_embed(self, x, grid_size: Optional[Tuple[int, int]] = None):
        """Add abs pos embed + prefix tokens; return (tokens, rope table)
        (reference eva.py:865-918)."""
        B = x.shape[0]
        if self.dynamic_img_size and grid_size is not None:
            if self.pos_embed is not None:
                pos_embed = resample_abs_pos_embed(
                    self.pos_embed[...].astype(x.dtype),
                    new_size=grid_size,
                    old_size=self.patch_embed.grid_size,
                    num_prefix_tokens=0 if self.no_embed_class else self.num_prefix_tokens,
                )
            else:
                pos_embed = None
            rope = self.rope.get_embed(shape=grid_size) if self.rope is not None else None
        else:
            pos_embed = self.pos_embed[...].astype(x.dtype) if self.pos_embed is not None else None
            rope = self.rope.get_embed() if self.rope is not None else None

        to_cat = []
        if self.cls_token is not None:
            to_cat.append(jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1])))
        if self.reg_token is not None:
            to_cat.append(jnp.broadcast_to(self.reg_token[...].astype(x.dtype), (B, self.num_reg_tokens, x.shape[-1])))
        if self.no_embed_class:
            if pos_embed is not None:
                x = x + pos_embed
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
        else:
            if to_cat:
                x = jnp.concatenate(to_cat + [x], axis=1)
            if pos_embed is not None:
                x = x + pos_embed
        return self.pos_drop(x), rope

    def _forward_blocks(self, x, rope, attn_mask=None):
        if self.block_scan:
            try:
                dp = drop_path_scan_inputs(self.blocks)
                # mixed rope is a per-depth table: thread it through the scan
                # as data; a shared rope table is a closure constant
                mixed = self.rope_mixed and rope is not None
                per_layer = {'dp': dp, 'rope': rope if mixed else None}

                def call(blk, xx, extra):
                    blk_rope = extra['rope'] if mixed else rope
                    return blk(xx, rope=blk_rope, attn_mask=attn_mask,
                               drop_path_override=extra['dp'])

                return scan_block_stack(
                    self.blocks, x, call, per_layer=per_layer,
                    remat=self.grad_checkpointing)
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e)
        from ..parallel import shard_activation
        x = shard_activation(x, 'residual')
        remat_block = None
        if self.grad_checkpointing:
            def run_block(blk, x_, rope_, mask_):
                return blk(x_, rope=rope_, attn_mask=mask_)
            remat_block = nnx.remat(run_block)
        for i, blk in enumerate(self.blocks):
            # mixed rope: depth-dependent table (depth, num_heads, N, head_dim)
            blk_rope = rope[i] if (self.rope_mixed and rope is not None) else rope
            if remat_block is not None:
                x = remat_block(blk, x, blk_rope, attn_mask)
            else:
                x = blk(x, rope=blk_rope, attn_mask=attn_mask)
            x = shard_activation(x, 'residual')
        return x

    def forward_features(self, x, attn_mask=None):
        grid_size = self.patch_embed.dynamic_feat_size(x.shape[1:3]) if self.dynamic_img_size else None
        x = self.patch_embed(x)
        x, rope = self._pos_embed(x, grid_size=grid_size)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        x = self._forward_blocks(x, rope, attn_mask=attn_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        if self.attn_pool is not None:
            x = self.attn_pool(x)
        else:
            x = global_pool_nlc(x, pool_type=self.global_pool, num_prefix_tokens=self.num_prefix_tokens)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, return_prefix_tokens: bool = False, norm: bool = False,
            stop_early: bool = False, output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B, H, W, _ = x.shape
        grid = self.patch_embed.dynamic_feat_size((H, W)) if self.dynamic_img_size \
            else self.patch_embed.grid_size
        x = self.patch_embed(x)
        x, rope = self._pos_embed(x, grid_size=grid if self.dynamic_img_size else None)
        if self.norm_pre is not None:
            x = self.norm_pre(x)
        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x, rope=rope[i] if (self.rope_mixed and rope is not None) else rope)
            if i in take_indices:
                y = self.norm(x) if (norm and self.norm is not None) else x
                prefix = y[:, :self.num_prefix_tokens] if self.num_prefix_tokens else None
                y = y[:, self.num_prefix_tokens:]
                if output_fmt == 'NHWC':
                    y = y.reshape(B, grid[0], grid[1], -1)
                intermediates.append((y, prefix) if return_prefix_tokens and prefix is not None else y)
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.reset_classifier(0)
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': 0.9, 'interpolation': 'bicubic', 'fixed_input_size': True,
        'mean': (0.48145466, 0.4578275, 0.40821073), 'std': (0.26862954, 0.26130258, 0.27577711),
        'first_conv': 'patch_embed.proj', 'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'eva02_tiny_patch14_336.mim_in22k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0),
    'eva02_small_patch14_336.mim_in22k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0),
    'eva02_base_patch14_448.mim_in22k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0),
    'eva02_large_patch14_448.mim_m38m_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0),
    'eva02_enormous_patch14_clip_224.untrained': _cfg(
        input_size=(3, 224, 224), num_classes=1024),
    'eva_giant_patch14_224.clip_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva_giant_patch14_336.clip_ft_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva_giant_patch14_336.m30m_ft_in22k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva_giant_patch14_560.m30m_ft_in22k_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 560, 560), crop_pct=1.0, crop_mode='squash', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_tiny_patch14_224.mim_in22k': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_small_patch14_224.mim_in22k': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_base_patch14_224.mim_in22k': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_large_patch14_224.mim_in22k': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_large_patch14_224.mim_m38m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva_giant_patch14_clip_224.laion400m': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva_giant_patch14_clip_224.merged2b': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_base_patch16_clip_224.merged2b': _cfg(hf_hub_id='timm/', num_classes=512, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_large_patch14_clip_224.merged2b': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 224, 224), crop_pct=0.9, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'eva02_large_patch14_clip_336.merged2b': _cfg(hf_hub_id='timm/', num_classes=768, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_medium_patch16_rope_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_mediumd_patch16_rope_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_betwixt_patch16_rope_reg4_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95, mean=(0.48145466, 0.4578275, 0.40821073), std=(0.26862954, 0.26130258, 0.27577711), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_rope_reg1_gap_256.sbb_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), crop_pct=0.95, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_core_tiny_patch16_384.fb': _cfg(hf_hub_id='timm/', num_classes=512, input_size=(3, 384, 384), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_core_small_patch16_384.fb': _cfg(hf_hub_id='timm/', num_classes=512, input_size=(3, 384, 384), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_core_base_patch16_224.fb': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 224, 224), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_core_large_patch14_336.fb': _cfg(hf_hub_id='timm/', num_classes=1024, input_size=(3, 336, 336), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_core_gigantic_patch14_448.fb': _cfg(hf_hub_id='timm/', num_classes=1280, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_lang_large_patch14_448.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_lang_large_patch14_448.fb_tiling': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_lang_gigantic_patch14_448.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_lang_gigantic_patch14_448.fb_tiling': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_spatial_tiny_patch16_512.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_spatial_small_patch16_512.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_spatial_base_patch16_512.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 512, 512), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_spatial_large_patch14_448.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_pe_spatial_gigantic_patch14_448.fb': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 448, 448), crop_pct=1.0, mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_rope_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_rope_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_rope_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_rope_mixed_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_rope_mixed_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_rope_mixed_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_rope_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_rope_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_rope_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_rope_mixed_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_rope_mixed_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_rope_mixed_ape_224.naver_in1k': _cfg(hf_hub_id='timm/', input_size=(3, 224, 224), crop_pct=0.9, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_tiny_patch16_dinov3_qkvb.eupe_lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_dinov3_qkvb.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_patch16_dinov3_qkvb.eupe_lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_plus_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_small_plus_patch16_dinov3_qkvb.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_dinov3_qkvb.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_base_patch16_dinov3_qkvb.eupe_lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_dinov3.sat493m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.43, 0.411, 0.296), std=(0.213, 0.156, 0.143), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_dinov3_qkvb.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_large_patch16_dinov3_qkvb.sat493m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.43, 0.411, 0.296), std=(0.213, 0.156, 0.143), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_huge_plus_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_huge_plus_patch16_dinov3_qkvb.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_7b_patch16_dinov3.lvd1689m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'vit_7b_patch16_dinov3.sat493m': _cfg(hf_hub_id='timm/', num_classes=0, input_size=(3, 256, 256), crop_pct=1.0, mean=(0.43, 0.411, 0.296), std=(0.213, 0.156, 0.143), fixed_input_size=True, first_conv='patch_embed.proj', classifier='head'),
    'test_eva.untrained': _cfg(input_size=(3, 160, 160)),
})


def checkpoint_filter_fn(state_dict: Dict, model) -> Dict:
    """Map reference-timm EVA layouts: raw gamma_1/gamma_2 layer-scale params
    → ls1/ls2 modules (reference eva.py:344,380 naming)."""
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = k.replace('gamma_1', 'ls1.gamma').replace('gamma_2', 'ls2.gamma')
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_eva(variant: str, pretrained: bool = False, **kwargs) -> Eva:
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        Eva, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def eva02_tiny_patch14_336(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=336, patch_size=14, embed_dim=192, depth=12, num_heads=3,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, use_rot_pos_emb=True, ref_feat_shape=(16, 16))
    return _create_eva('eva02_tiny_patch14_336', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_small_patch14_336(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=336, patch_size=14, embed_dim=384, depth=12, num_heads=6,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, use_rot_pos_emb=True, ref_feat_shape=(16, 16))
    return _create_eva('eva02_small_patch14_336', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_base_patch14_448(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=448, patch_size=14, embed_dim=768, depth=12, num_heads=12,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True,
        qkv_fused=False, ref_feat_shape=(16, 16))
    return _create_eva('eva02_base_patch14_448', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_large_patch14_448(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=448, patch_size=14, embed_dim=1024, depth=24, num_heads=16,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True,
        qkv_fused=False, ref_feat_shape=(16, 16))
    return _create_eva('eva02_large_patch14_448', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_enormous_patch14_clip_224(pretrained=False, **kwargs) -> Eva:
    """EVA-CLIP variant with residual post-norm blocks (reference eva.py:2068;
    post-norm numerics parity-verified at small scale: 1.2e-10)."""
    model_args = dict(
        img_size=224, patch_size=14, embed_dim=1792, depth=64, num_heads=16,
        mlp_ratio=15360 / 1792, use_post_norm=True)
    return _create_eva('eva02_enormous_patch14_clip_224', pretrained, **dict(model_args, **kwargs))


@register_model
def test_eva(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2,
        mlp_ratio=8 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True, init_values=1e-5)
    return _create_eva('test_eva', pretrained, **dict(model_args, **kwargs))


@register_model
def eva_giant_patch14_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA-g model https://arxiv.org/abs/2211.07636"""
    model_args = dict(patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=6144 / 1408)
    return _create_eva('eva_giant_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva_giant_patch14_336(pretrained: bool = False, **kwargs) -> Eva:
    """EVA-g model https://arxiv.org/abs/2211.07636"""
    model_args = dict(patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=6144 / 1408)
    return _create_eva('eva_giant_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva_giant_patch14_560(pretrained: bool = False, **kwargs) -> Eva:
    """EVA-g model https://arxiv.org/abs/2211.07636"""
    model_args = dict(patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=6144 / 1408)
    return _create_eva('eva_giant_patch14_560', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_tiny_patch14_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA02 Tiny https://arxiv.org/abs/2303.11331"""
    model_args = dict(
        img_size=224,
        patch_size=14,
        embed_dim=192,
        depth=12,
        num_heads=3,
        mlp_ratio=4 * 2 / 3,
        swiglu_mlp=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('eva02_tiny_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_small_patch14_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA02 Small https://arxiv.org/abs/2303.11331"""
    model_args = dict(
        img_size=224,
        patch_size=14,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4 * 2 / 3,
        swiglu_mlp=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('eva02_small_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_base_patch14_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA02 Base https://arxiv.org/abs/2303.11331"""
    model_args = dict(
        img_size=224,
        patch_size=14,
        embed_dim=768,
        depth=12,
        num_heads=12,
        qkv_fused=False,
        mlp_ratio=4 * 2 / 3,
        swiglu_mlp=True,
        scale_mlp=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('eva02_base_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_large_patch14_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA02 Large https://arxiv.org/abs/2303.11331"""
    model_args = dict(
        img_size=224,
        patch_size=14,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4 * 2 / 3,
        qkv_fused=False,
        swiglu_mlp=True,
        scale_mlp=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('eva02_large_patch14_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva_giant_patch14_clip_224(pretrained: bool = False, **kwargs) -> Eva:
    """EVA-g CLIP model (only difference from non-CLIP is the pooling)"""
    model_args = dict(
        patch_size=14, embed_dim=1408, depth=40, num_heads=16, mlp_ratio=6144 / 1408,
        global_pool=kwargs.pop('global_pool', 'token'))
    return _create_eva('eva_giant_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_base_patch16_clip_224(pretrained: bool = False, **kwargs) -> Eva:
    """An EVA-CLIP specific variant that adds additional attn scale layer-norm to eva02_base"""
    model_args = dict(
        img_size=224,
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        qkv_fused=False,
        mlp_ratio=4 * 2 / 3,
        swiglu_mlp=True,
        scale_mlp=True,
        scale_attn_inner=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
        global_pool=kwargs.pop('global_pool', 'token'),
    )
    return _create_eva('eva02_base_patch16_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_large_patch14_clip_224(pretrained: bool = False, **kwargs) -> Eva:
    """An EVA-CLIP specific variant that adds additional attn scale layer-norm to eva02_large"""
    model_args = dict(
        img_size=224,
        patch_size=14,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4 * 2 / 3,
        qkv_fused=False,
        swiglu_mlp=True,
        scale_mlp=True,
        scale_attn_inner=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
        global_pool=kwargs.pop('global_pool', 'token'),
    )
    return _create_eva('eva02_large_patch14_clip_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_large_patch14_clip_336(pretrained: bool = False, **kwargs) -> Eva:
    """An EVA-CLIP specific variant that adds additional attn scale layer-norm to eva02_large"""
    model_args = dict(
        img_size=336,
        patch_size=14,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4 * 2 / 3,
        qkv_fused=False,
        swiglu_mlp=True,
        scale_mlp=True,
        scale_attn_inner=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(16, 16),  # 224/14
        global_pool=kwargs.pop('global_pool', 'token'),
    )
    return _create_eva('eva02_large_patch14_clip_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_medium_patch16_rope_reg1_gap_256(pretrained: bool = False, **kwargs) -> Eva:
    """timm SBB ViT with ROPE"""
    model_args = dict(
        img_size=256,
        patch_size=16,
        embed_dim=512,
        depth=12,
        num_heads=8,
        qkv_fused=True,
        qkv_bias=True,
        init_values=1e-5,
        class_token=False,
        num_reg_tokens=1,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('vit_medium_patch16_rope_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_mediumd_patch16_rope_reg1_gap_256(pretrained: bool = False, **kwargs) -> Eva:
    """timm SBB ViT with ROPE"""
    model_args = dict(
        img_size=256,
        patch_size=16,
        embed_dim=512,
        depth=20,
        num_heads=8,
        qkv_fused=True,
        qkv_bias=False,
        init_values=1e-5,
        class_token=False,
        num_reg_tokens=1,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('vit_mediumd_patch16_rope_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_betwixt_patch16_rope_reg4_gap_256(pretrained: bool = False, **kwargs) -> Eva:
    """timm SBB ViT with ROPE"""
    model_args = dict(
        img_size=256,
        patch_size=16,
        embed_dim=640,
        depth=12,
        num_heads=10,
        qkv_fused=True,
        qkv_bias=True,
        init_values=1e-5,
        class_token=False,
        num_reg_tokens=4,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('vit_betwixt_patch16_rope_reg4_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rope_reg1_gap_256(pretrained: bool = False, **kwargs) -> Eva:
    """timm SBB ViT with ROPE"""
    model_args = dict(
        img_size=256,
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        qkv_fused=True,
        qkv_bias=True,
        init_values=1e-5,
        class_token=False,
        num_reg_tokens=1,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        ref_feat_shape=(16, 16),  # 224/14
    )
    return _create_eva('vit_base_patch16_rope_reg1_gap_256', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_core_tiny_patch16_384(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=192,
        depth=12,
        num_heads=3,
        mlp_ratio=4.0,
        global_pool='map',
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(24, 24),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        attn_pool_num_heads=8,
        attn_pool_mlp_ratio=4.,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_core_tiny_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_core_small_patch16_384(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4.0,
        global_pool='map',
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(24, 24),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        attn_pool_num_heads=8,
        attn_pool_mlp_ratio=4.,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_core_small_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_core_base_patch16_224(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4.0,
        global_pool='map',
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(14, 14),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        attn_pool_num_heads=8,
        attn_pool_mlp_ratio=4.,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_core_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_core_large_patch14_336(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4.0,
        global_pool='map',
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(24, 24),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        attn_pool_num_heads=8,
        attn_pool_mlp_ratio=4.,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_core_large_patch14_336', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_core_gigantic_patch14_448(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1536,
        depth=50,
        num_heads=16,
        mlp_ratio=8960 / 1536,
        global_pool='map',
        attn_type='rope',
        class_token=False,
        use_pre_transformer_norm=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_indexing='xy',
        attn_pool_num_heads=8,
        attn_pool_mlp_ratio=4.,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_core_gigantic_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_lang_large_patch14_448(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1024,
        depth=23,
        num_heads=16,
        mlp_ratio=4.0,
        attn_type='rope',
        class_token=True,
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        init_values=0.1,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_lang_large_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_lang_gigantic_patch14_448(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1536,
        depth=47,
        num_heads=16,
        mlp_ratio=8960 / 1536,
        attn_type='rope',
        class_token=False,
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_indexing='xy',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        init_values=0.1,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_lang_gigantic_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_spatial_tiny_patch16_512(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=192,
        depth=12,
        num_heads=3,
        mlp_ratio=4.0,
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_spatial_tiny_patch16_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_spatial_small_patch16_512(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4.0,
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_spatial_small_patch16_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_spatial_base_patch16_512(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4.0,
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True
    )
    return _create_eva('vit_pe_spatial_base_patch16_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_spatial_large_patch14_448(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4.0,
        attn_type='rope',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_offset=1.,
        rope_grid_indexing='xy',
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_spatial_large_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_pe_spatial_gigantic_patch14_448(pretrained: bool = False, **kwargs) -> Eva:
    """Perception Encoder (PE) ViT from Meta (https://arxiv.org/abs/2504.13181)"""
    model_args = dict(
        patch_size=14,
        embed_dim=1536,
        depth=50,
        num_heads=16,
        mlp_ratio=8960 / 1536,
        attn_type='rope',
        class_token=False,
        use_rot_pos_emb=True,
        ref_feat_shape=(32, 32),
        rope_grid_indexing='xy',
        use_pre_transformer_norm=True,
        use_post_transformer_norm=False,
        use_fc_norm=False,  # explicitly disable
        init_values=0.1,
        norm_layer=partial(LayerNorm, eps=1e-5),
        #dynamic_img_size=True,
    )
    return _create_eva('vit_pe_spatial_gigantic_patch14_448', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_rope_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial ViT-S/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_small_patch16_rope_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rope_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial ViT-B/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4,
        attn_type='rope',
        use_fc_norm=False,
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_base_patch16_rope_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_rope_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial ViT-L/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_large_patch16_rope_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_rope_mixed_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed ViT-S/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_small_patch16_rope_mixed_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rope_mixed_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed ViT-B/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4,
        qkv_bias=True,
        attn_type='rope',
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_base_patch16_rope_mixed_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_rope_mixed_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed ViT-L/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        use_abs_pos_emb=False,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_large_patch16_rope_mixed_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_rope_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial + APE ViT-S/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_small_patch16_rope_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rope_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial + APE ViT-B/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_base_patch16_rope_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_rope_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Axial + APE ViT-L/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=100.0,
    )
    return _create_eva('vit_large_patch16_rope_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_rope_mixed_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed + APE ViT-S/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=384,
        depth=12,
        num_heads=6,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_small_patch16_rope_mixed_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_rope_mixed_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed + APE ViT-B/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=768,
        depth=12,
        num_heads=12,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_base_patch16_rope_mixed_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_rope_mixed_ape_224(pretrained: bool = False, **kwargs) -> Eva:
    """RoPE-Mixed + APE ViT-L/16 from https://github.com/naver-ai/rope-vit"""
    model_args = dict(
        patch_size=16,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        mlp_ratio=4,
        attn_type='rope',
        qkv_bias=True,
        init_values=1e-5,
        class_token=True,
        global_pool='token',
        no_embed_class=True,
        use_abs_pos_emb=True,
        use_rot_pos_emb=True,
        rope_grid_indexing='xy',
        rope_temperature=10.0,
        rope_type='mixed'
    )
    return _create_eva('vit_large_patch16_rope_mixed_ape_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_tiny_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3-style T/16 w/ QKV bias enabled."""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=192,
        depth=12,
        num_heads=3,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_tiny_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 S/16 https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=384,
        depth=12,
        num_heads=6,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_small_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 S/16 w/ QKV bias enabled (but zero) https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=384,
        depth=12,
        num_heads=6,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_small_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_plus_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 S/16 Plus https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=384,
        depth=12,
        num_heads=6,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        swiglu_mlp=True,
        swiglu_align_to=8,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_small_plus_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_small_plus_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 S/16 Plus w/ QKV bias enabled (but 0) https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=384,
        depth=12,
        num_heads=6,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        swiglu_mlp=True,
        swiglu_align_to=8,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_small_plus_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 B/16 https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=768,
        depth=12,
        num_heads=12,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_base_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_base_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 B/16 w/ QKV bias enabled (but zero) https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=768,
        depth=12,
        num_heads=12,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-05, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        #rope_rescale_coords=2,  # haven't added to interface
        rope_rotate_half=True,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_base_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 L/16 https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-5, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        rope_rotate_half=True,
        #rope_rescale_coords=2,  # haven't added to interface
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_large_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_large_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 w/ QKV bias enabled (but zero) https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=1024,
        depth=24,
        num_heads=16,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-5, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        rope_rotate_half=True,
        #rope_rescale_coords=2,  # haven't added to interface
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_large_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_plus_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 H/16 Plus https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=1280,
        depth=32,
        num_heads=20,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-5, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        rope_rotate_half=True,
        swiglu_mlp=True,
        swiglu_align_to=8,
        #rope_rescale_coords=2,  # haven't added to interface
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_huge_plus_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_huge_plus_patch16_dinov3_qkvb(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 H/16 Plus w/ QKV bias enabled (but zero) https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=1280,
        depth=32,
        num_heads=20,
        qkv_bias=True,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        init_values=1.0e-5, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        rope_rotate_half=True,
        swiglu_mlp=True,
        swiglu_align_to=8,
        #rope_rescale_coords=2,  # haven't added to interface
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_huge_plus_patch16_dinov3_qkvb', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def vit_7b_patch16_dinov3(pretrained: bool = False, **kwargs) -> Eva:
    """DINOv3 7B/16 https://arxiv.org/abs/2508.10104"""
    model_args = dict(
        patch_size=16,
        dynamic_img_size=True,
        embed_dim=4096,
        depth=40,
        num_heads=32,
        qkv_bias=False,
        # global_pool='token',  # upstream uses CLS token; default here is 'avg', pass via kwargs or --gp
        mlp_ratio=2,
        init_values=1.0e-5, # layer-scale
        rope_type='dinov3',
        rope_temperature=100,
        use_rot_pos_emb=True,
        use_abs_pos_emb=False,
        rope_rotate_half=True,
        swiglu_mlp=True,
        swiglu_align_to=64,
        #rope_rescale_coords=2,  # haven't added to interface
        num_reg_tokens=4,
        use_fc_norm=False,
        norm_layer=partial(LayerNorm, eps=1e-5),
    )
    return _create_eva('vit_7b_patch16_dinov3', pretrained=pretrained, **dict(model_args, **kwargs))
