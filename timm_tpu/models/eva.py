"""EVA / EVA02 (reference: timm/models/eva.py:1-3096), TPU-native.

ViT with rotary position embeddings (shared per-model ROPE table, applied to
non-prefix tokens), optional SwiGLU MLP with inner norm, and pre/post-norm
block options. Covers the eva02 family (the reference zoo's top-1 leader).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import (
    Dropout, DropPath, GluMlp, LayerNorm, LayerScale, Mlp,
    PatchEmbed, RotaryEmbeddingCat, SwiGLU, calculate_drop_path_rates,
    get_norm_layer, global_pool_nlc, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['Eva', 'EvaBlock', 'EvaAttention']


class EvaAttention(nnx.Module):
    """ROPE attention with optional unfused q/k/v projections — eva02
    base/large checkpoints store separate q/k/v with no k bias
    (reference eva.py EvaAttention)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            norm_layer: Optional[Callable] = None,
            scale_norm: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        from functools import partial as _partial
        from ..layers.attention import scaled_dot_product_attention, apply_rot_embed_cat
        from ..layers.drop import Dropout as _Dropout, dropout_rng_key as _drk
        assert dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.scale = self.head_dim ** -0.5
        self.attn_drop_rate = attn_drop
        self.qkv_fused = qkv_fused
        self._sdpa = scaled_dot_product_attention
        self._rot = apply_rot_embed_cat
        self._drk = _drk

        linear = _partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        if qkv_fused:
            # reference layout: unbiased fused projection + separate q/v bias
            # params (k bias fixed at zero) — BEiT-style (reference eva.py:161)
            self.qkv = linear(dim, dim * 3, use_bias=False)
            self.q_proj = self.k_proj = self.v_proj = None
            if qkv_bias:
                self.q_bias = nnx.Param(jnp.zeros((dim,), param_dtype))
                self.v_bias = nnx.Param(jnp.zeros((dim,), param_dtype))
            else:
                self.q_bias = self.v_bias = None
        else:
            self.qkv = None
            self.q_bias = self.v_bias = None
            self.q_proj = linear(dim, dim, use_bias=qkv_bias)
            self.k_proj = linear(dim, dim, use_bias=False)
            self.v_proj = linear(dim, dim, use_bias=qkv_bias)
        self.q_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.k_norm = norm_layer(self.head_dim, rngs=rngs) if qk_norm else None
        self.attn_drop = _Dropout(attn_drop, rngs=rngs)
        self.norm = norm_layer(dim, rngs=rngs) if scale_norm else None
        self.proj = linear(dim, dim)
        self.proj_drop = _Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, rope=None, attn_mask=None):
        B, N, C = x.shape
        if self.qkv_fused:
            qkv = self.qkv(x)
            if self.q_bias is not None:
                bias = jnp.concatenate([
                    self.q_bias[...], jnp.zeros_like(self.q_bias[...]), self.v_bias[...]])
                qkv = qkv + bias.astype(qkv.dtype)
            qkv = qkv.reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
            q, k, v = qkv[0], qkv[1], qkv[2]
        else:
            q = self.q_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            k = self.k_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            v = self.v_proj(x).reshape(B, N, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        if self.q_norm is not None:
            q = self.q_norm(q)
        if self.k_norm is not None:
            k = self.k_norm(k)
        if rope is not None:
            num_prefix = N - rope.shape[-2]
            if num_prefix > 0:
                q = jnp.concatenate([q[..., :num_prefix, :], self._rot(q[..., num_prefix:, :], rope)], axis=-2)
                k = jnp.concatenate([k[..., :num_prefix, :], self._rot(k[..., num_prefix:, :], rope)], axis=-2)
            else:
                q, k = self._rot(q, rope), self._rot(k, rope)
            q = q.astype(v.dtype)
            k = k.astype(v.dtype)
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop_rate
        dropout_key = self._drk(self.attn_drop) if dropout_p > 0.0 else None
        x = self._sdpa(q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
                       dropout_key=dropout_key, scale=self.scale)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, C)
        if self.norm is not None:
            x = self.norm(x)
        x = self.proj(x)
        return self.proj_drop(x)


class EvaBlock(nnx.Module):
    def __init__(
            self,
            dim: int,
            num_heads: int,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            mlp_ratio: float = 4.0,
            swiglu_mlp: bool = False,
            scale_mlp: bool = False,
            scale_attn_inner: bool = False,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: float = 0.0,
            init_values: Optional[float] = None,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            use_post_norm: bool = False,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        # post-norm (beit3-style, reference eva.py EvaBlockPostNorm:430-525):
        # residual adds norm(branch(x)) and layer scale is ignored
        self.use_post_norm = use_post_norm
        if use_post_norm:
            init_values = None
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = EvaAttention(
            dim,
            num_heads=num_heads,
            qkv_bias=qkv_bias,
            qkv_fused=qkv_fused,
            qk_norm=qk_norm,
            attn_drop=attn_drop,
            proj_drop=proj_drop,
            norm_layer=norm_layer,
            scale_norm=scale_attn_inner,
            dtype=dtype,
            param_dtype=param_dtype,
            rngs=rngs,
        )
        self.ls1 = LayerScale(dim, init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        hidden = int(dim * mlp_ratio)
        if swiglu_mlp:
            if scale_mlp:
                # norm requires the un-packed variant (reference eva.py block init)
                self.mlp = SwiGLU(
                    dim, hidden, norm_layer=norm_layer,
                    drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            else:
                # packed weights (one fc1) to match eva02 tiny/small checkpoints
                self.mlp = GluMlp(
                    dim, hidden * 2, act_layer='silu', gate_last=False,
                    drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.mlp = Mlp(
                dim, hidden, act_layer=act_layer,
                norm_layer=norm_layer if scale_mlp else None,
                drop=proj_drop, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.ls2 = LayerScale(dim, init_values, param_dtype=param_dtype, rngs=rngs) if init_values else None
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x, rope=None, attn_mask=None):
        if self.use_post_norm:
            x = x + self.drop_path1(self.norm1(self.attn(x, rope=rope, attn_mask=attn_mask)))
            x = x + self.drop_path2(self.norm2(self.mlp(x)))
            return x
        y = self.attn(self.norm1(x), rope=rope, attn_mask=attn_mask)
        if self.ls1 is not None:
            y = self.ls1(y)
        x = x + self.drop_path1(y)
        y = self.mlp(self.norm2(x))
        if self.ls2 is not None:
            y = self.ls2(y)
        x = x + self.drop_path2(y)
        return x


class Eva(nnx.Module):
    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: int = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            qkv_bias: bool = True,
            qkv_fused: bool = True,
            qk_norm: bool = False,
            mlp_ratio: float = 4.0,
            swiglu_mlp: bool = False,
            scale_mlp: bool = False,
            scale_attn_inner: bool = False,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            init_values: Optional[float] = None,
            class_token: bool = True,
            num_reg_tokens: int = 0,
            use_abs_pos_emb: bool = True,
            use_rot_pos_emb: bool = False,
            ref_feat_shape: Optional[Tuple[int, int]] = None,
            rope_grid_offset: float = 0.0,
            rope_grid_indexing: str = 'ij',
            use_post_norm: bool = False,
            norm_layer: Optional[Union[str, Callable]] = None,
            act_layer: Union[str, Callable] = 'gelu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.num_prefix_tokens = (1 if class_token else 0) + num_reg_tokens
        self.num_reg_tokens = num_reg_tokens
        self.grad_checkpointing = False

        self.patch_embed = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans, embed_dim=embed_dim,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        num_patches = self.patch_embed.num_patches

        self.cls_token = nnx.Param(jnp.zeros((1, 1, embed_dim), param_dtype)) if class_token else None
        self.reg_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, num_reg_tokens, embed_dim), param_dtype)) \
            if num_reg_tokens else None

        if use_abs_pos_emb:
            self.pos_embed = nnx.Param(trunc_normal_(std=0.02)(
                rngs.params(), (1, num_patches + self.num_prefix_tokens, embed_dim), param_dtype))
        else:
            self.pos_embed = None
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        if use_rot_pos_emb:
            self.rope = RotaryEmbeddingCat(
                embed_dim // num_heads,
                in_pixels=False,
                feat_shape=self.patch_embed.grid_size,
                ref_feat_shape=ref_feat_shape,
                grid_offset=rope_grid_offset,
                grid_indexing=rope_grid_indexing,
            )
        else:
            self.rope = None

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            EvaBlock(
                dim=embed_dim,
                num_heads=num_heads,
                qkv_bias=qkv_bias,
                qkv_fused=qkv_fused,
                qk_norm=qk_norm,
                mlp_ratio=mlp_ratio,
                swiglu_mlp=swiglu_mlp,
                scale_mlp=scale_mlp,
                scale_attn_inner=scale_attn_inner,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                init_values=init_values,
                act_layer=act_layer,
                norm_layer=norm_layer,
                use_post_norm=use_post_norm,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        reduction = self.patch_embed.patch_size[0]
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=reduction) for i in range(depth)]

        use_fc_norm = global_pool == 'avg'
        self.norm = norm_layer(embed_dim, rngs=rngs) if not use_fc_norm else None
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if use_fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'reg_token'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|reg_token',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm|^fc_norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def _pos_embed(self, x):
        B = x.shape[0]
        to_cat = []
        if self.cls_token is not None:
            to_cat.append(jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1])))
        if self.reg_token is not None:
            to_cat.append(jnp.broadcast_to(self.reg_token[...].astype(x.dtype), (B, self.num_reg_tokens, x.shape[-1])))
        if to_cat:
            x = jnp.concatenate(to_cat + [x], axis=1)
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)
        return self.pos_drop(x)

    def forward_features(self, x, attn_mask=None):
        x = self.patch_embed(x)
        x = self._pos_embed(x)
        rope = self.rope.get_embed() if self.rope is not None else None
        if self.grad_checkpointing:
            def run_block(blk, x_, rope_, mask_):
                return blk(x_, rope=rope_, attn_mask=mask_)
            remat_block = nnx.remat(run_block)
            for blk in self.blocks:
                x = remat_block(blk, x, rope, attn_mask)
        else:
            for blk in self.blocks:
                x = blk(x, rope=rope, attn_mask=attn_mask)
        if self.norm is not None:
            x = self.norm(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = global_pool_nlc(x, pool_type=self.global_pool, num_prefix_tokens=self.num_prefix_tokens)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, return_prefix_tokens: bool = False, norm: bool = False,
            stop_early: bool = False, output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B, H, W, _ = x.shape
        grid = self.patch_embed.grid_size
        x = self.patch_embed(x)
        x = self._pos_embed(x)
        rope = self.rope.get_embed() if self.rope is not None else None
        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x, rope=rope)
            if i in take_indices:
                y = self.norm(x) if (norm and self.norm is not None) else x
                prefix = y[:, :self.num_prefix_tokens] if self.num_prefix_tokens else None
                y = y[:, self.num_prefix_tokens:]
                if output_fmt == 'NHWC':
                    y = y.reshape(B, grid[0], grid[1], -1)
                intermediates.append((y, prefix) if return_prefix_tokens and prefix is not None else y)
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.reset_classifier(0)
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url, 'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': 0.9, 'interpolation': 'bicubic', 'fixed_input_size': True,
        'mean': (0.48145466, 0.4578275, 0.40821073), 'std': (0.26862954, 0.26130258, 0.27577711),
        'first_conv': 'patch_embed.proj', 'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'eva02_tiny_patch14_336.mim_in22k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0),
    'eva02_small_patch14_336.mim_in22k_ft_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 336, 336), crop_pct=1.0),
    'eva02_base_patch14_448.mim_in22k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0),
    'eva02_large_patch14_448.mim_m38m_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 448, 448), crop_pct=1.0),
    'eva02_enormous_patch14_clip_224.untrained': _cfg(
        input_size=(3, 224, 224), num_classes=1024),
    'test_eva.untrained': _cfg(input_size=(3, 160, 160)),
})


def _create_eva(variant: str, pretrained: bool = False, **kwargs) -> Eva:
    from ._torch_convert import convert_torch_state_dict
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        Eva, variant, pretrained,
        pretrained_filter_fn=convert_torch_state_dict,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def eva02_tiny_patch14_336(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=336, patch_size=14, embed_dim=192, depth=12, num_heads=3,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, use_rot_pos_emb=True, ref_feat_shape=(16, 16))
    return _create_eva('eva02_tiny_patch14_336', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_small_patch14_336(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=336, patch_size=14, embed_dim=384, depth=12, num_heads=6,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, use_rot_pos_emb=True, ref_feat_shape=(16, 16))
    return _create_eva('eva02_small_patch14_336', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_base_patch14_448(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=448, patch_size=14, embed_dim=768, depth=12, num_heads=12,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True,
        qkv_fused=False, ref_feat_shape=(16, 16))
    return _create_eva('eva02_base_patch14_448', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_large_patch14_448(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=448, patch_size=14, embed_dim=1024, depth=24, num_heads=16,
        mlp_ratio=4 * 2 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True,
        qkv_fused=False, ref_feat_shape=(16, 16))
    return _create_eva('eva02_large_patch14_448', pretrained, **dict(model_args, **kwargs))


@register_model
def eva02_enormous_patch14_clip_224(pretrained=False, **kwargs) -> Eva:
    """EVA-CLIP variant with residual post-norm blocks (reference eva.py:2068;
    post-norm numerics parity-verified at small scale: 1.2e-10)."""
    model_args = dict(
        img_size=224, patch_size=14, embed_dim=1792, depth=64, num_heads=16,
        mlp_ratio=15360 / 1792, use_post_norm=True)
    return _create_eva('eva02_enormous_patch14_clip_224', pretrained, **dict(model_args, **kwargs))


@register_model
def test_eva(pretrained=False, **kwargs) -> Eva:
    model_args = dict(
        img_size=160, patch_size=16, embed_dim=64, depth=2, num_heads=2,
        mlp_ratio=8 / 3, swiglu_mlp=True, scale_mlp=True, use_rot_pos_emb=True, init_values=1e-5)
    return _create_eva('test_eva', pretrained, **dict(model_args, **kwargs))
