"""Res2Net: multi-scale residual blocks on the ResNet trunk, TPU-native NHWC
(reference: timm/models/res2net.py:1-240; Gao et al. 2019).

The Bottle2neck splits the bottleneck width into `scale` groups processed by
a cascade of 3x3 convs with cross-group additive feedthrough — expressed here
as static channel slices (XLA fuses the concat back into the 1x1 projection).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, create_conv2d, get_act_fn
from ..layers.drop import DropPath
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .resnet import ResNet, checkpoint_filter_fn

__all__ = ['Bottle2neck']


def _avg_pool3s_pad1(x, stride: int):
    """AvgPool2d(3, stride, padding=1) with count_include_pad=True (the
    reference keeps torch defaults here to match original weights)."""
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    s = jax.lax.reduce_window(
        xp, 0.0, jax.lax.add, (1, 3, 3, 1), (1, stride, stride, 1), 'VALID')
    return s / 9.0


class Bottle2neck(nnx.Module):
    """Res2Net bottleneck (reference res2net.py:20-130)."""
    expansion = 4

    def __init__(
            self,
            inplanes: int,
            planes: int,
            stride: int = 1,
            downsample=None,
            cardinality: int = 1,
            base_width: int = 26,
            scale: int = 4,
            reduce_first: int = 1,
            dilation: int = 1,
            first_dilation: Optional[int] = None,
            act_layer='relu',
            norm_layer: Callable = BatchNormAct2d,
            attn_layer: Optional[Callable] = None,
            aa_layer: Optional[Callable] = None,
            drop_path: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert aa_layer is None, 'aa_layer not supported by Bottle2neck'
        self.scale = scale
        self.is_first = stride > 1 or downsample is not None
        self.num_scales = max(1, scale - 1)
        width = int(math.floor(planes * (base_width / 64.0))) * cardinality
        self.width = width
        outplanes = planes * self.expansion
        first_dilation = first_dilation or dilation
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.conv1 = create_conv2d(inplanes, width * scale, 1, **kw)
        self.bn1 = norm_layer(width * scale, act_layer=act_layer, **kw)
        self.convs = nnx.List([
            create_conv2d(width, width, 3, stride=stride, dilation=first_dilation,
                          groups=cardinality, padding=None, **kw)
            for _ in range(self.num_scales)
        ])
        self.bns = nnx.List([
            norm_layer(width, act_layer=act_layer, **kw) for _ in range(self.num_scales)])
        self.pool_stride = stride if self.is_first else None
        self.conv3 = create_conv2d(width * scale, outplanes, 1, **kw)
        self.bn3 = norm_layer(outplanes, apply_act=False, **kw)
        self.se = attn_layer(outplanes, dtype=dtype, param_dtype=param_dtype, rngs=rngs) \
            if attn_layer is not None else None
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.bn3, 'scale'):
            self.bn3.scale[...] = jnp.zeros_like(self.bn3.scale[...])

    def __call__(self, x):
        shortcut = x
        out = self.bn1(self.conv1(x))
        spx = [out[..., i * self.width:(i + 1) * self.width] for i in range(self.scale)]
        spo = []
        sp = spx[0]
        for i, (conv, bn) in enumerate(zip(self.convs, self.bns)):
            if i == 0 or self.is_first:
                sp = spx[i]
            else:
                sp = sp + spx[i]
            sp = bn(conv(sp))
            spo.append(sp)
        if self.scale > 1:
            if self.pool_stride is not None:
                spo.append(_avg_pool3s_pad1(spx[-1], self.pool_stride))
            else:
                spo.append(spx[-1])
        out = jnp.concatenate(spo, axis=-1)
        out = self.bn3(self.conv3(out))
        if self.se is not None:
            out = self.se(out)
        if self.downsample is not None:
            shortcut = self.downsample(x)
        out = self.drop_path(out) + shortcut
        return self.act(out)


def _create_res2net(variant, pretrained=False, **kwargs):
    # block_args in reference become direct block partial kwargs here
    block_args = kwargs.pop('block_args', {})
    block = kwargs.pop('block', Bottle2neck)
    if block_args:
        block = partial(block, **block_args)
        block.expansion = Bottle2neck.expansion
    return build_model_with_cfg(
        ResNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        block=block,
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bilinear',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv1', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'res2net50_26w_4s.in1k': _cfg(hf_hub_id='timm/'),
    'res2net50_48w_2s.in1k': _cfg(hf_hub_id='timm/'),
    'res2net50_14w_8s.in1k': _cfg(hf_hub_id='timm/'),
    'res2net50_26w_6s.in1k': _cfg(hf_hub_id='timm/'),
    'res2net50_26w_8s.in1k': _cfg(hf_hub_id='timm/'),
    'res2net101_26w_4s.in1k': _cfg(hf_hub_id='timm/'),
    'res2next50.in1k': _cfg(hf_hub_id='timm/'),
    'res2net50d.in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
    'res2net101d.in1k': _cfg(hf_hub_id='timm/', first_conv='conv1.0'),
})


@register_model
def res2net50_26w_4s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=26, block_args=dict(scale=4))
    return _create_res2net('res2net50_26w_4s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net101_26w_4s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 23, 3), base_width=26, block_args=dict(scale=4))
    return _create_res2net('res2net101_26w_4s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net50_26w_6s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=26, block_args=dict(scale=6))
    return _create_res2net('res2net50_26w_6s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net50_26w_8s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=26, block_args=dict(scale=8))
    return _create_res2net('res2net50_26w_8s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net50_48w_2s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=48, block_args=dict(scale=2))
    return _create_res2net('res2net50_48w_2s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net50_14w_8s(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=14, block_args=dict(scale=8))
    return _create_res2net('res2net50_14w_8s', pretrained, **dict(model_args, **kwargs))


@register_model
def res2next50(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(layers=(3, 4, 6, 3), base_width=4, cardinality=8, block_args=dict(scale=4))
    return _create_res2net('res2next50', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net50d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 6, 3), base_width=26, stem_type='deep', avg_down=True,
        stem_width=32, block_args=dict(scale=4))
    return _create_res2net('res2net50d', pretrained, **dict(model_args, **kwargs))


@register_model
def res2net101d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 23, 3), base_width=26, stem_type='deep', avg_down=True,
        stem_width=32, block_args=dict(scale=4))
    return _create_res2net('res2net101d', pretrained, **dict(model_args, **kwargs))
