"""ConvMixer (reference: timm/models/convmixer.py:1-150), TPU-native NHWC.

Patch-embed stem then depth x (residual dw conv + pw conv), each followed by
act + BN. NHWC keeps the pw conv a plain matmul on the MXU and the large-k
depthwise conv maps to the vector unit without layout shuffles.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNorm2d, SelectAdaptivePool2d, create_conv2d, get_act_fn, trunc_normal_, zeros_
from ..layers.drop import Dropout
from ._builder import build_model_with_cfg
from ._manipulate import checkpoint_seq
from ._registry import generate_default_cfgs, register_model

__all__ = ['ConvMixer']


class ConvMixerBlock(nnx.Module):
    """Residual dw conv (+act+BN) then pw conv (+act+BN)
    (reference convmixer.py:56-66 Sequential layout)."""

    def __init__(self, dim, kernel_size, act_layer, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv_dw = create_conv2d(dim, dim, kernel_size, padding='same', depthwise=True, bias=True, **kw)
        self.bn1 = BatchNorm2d(dim, rngs=rngs)
        self.conv_pw = create_conv2d(dim, dim, 1, bias=True, **kw)
        self.bn2 = BatchNorm2d(dim, rngs=rngs)
        self.act = get_act_fn(act_layer)

    def __call__(self, x):
        x = x + self.bn1(self.act(self.conv_dw(x)))
        return self.bn2(self.act(self.conv_pw(x)))


class ConvMixer(nnx.Module):
    """(reference convmixer.py:27-106)."""

    def __init__(
            self,
            dim: int,
            depth: int,
            kernel_size: int = 9,
            patch_size: int = 7,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            drop_rate: float = 0.0,
            act_layer: Union[str, Callable] = 'gelu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.num_features = self.head_hidden_size = dim
        self.grad_checkpointing = False

        self.stem_conv = create_conv2d(in_chans, dim, patch_size, stride=patch_size, padding=0, bias=True, **kw)
        self.stem_bn = BatchNorm2d(dim, rngs=rngs)
        self.act = get_act_fn(act_layer)
        self.blocks = nnx.List([
            ConvMixerBlock(dim, kernel_size, act_layer, **kw) for _ in range(depth)])
        self.feature_info = [dict(num_chs=dim, reduction=patch_size, module=f'blocks.{i}') for i in range(depth)]
        self.pooling = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        self.head = nnx.Linear(
            dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            **kw) if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=r'^blocks\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.pooling = SelectAdaptivePool2d(pool_type=global_pool, flatten=True)
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.num_features, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    def forward_features(self, x):
        x = self.stem_bn(self.act(self.stem_conv(x)))
        if self.grad_checkpointing:
            x = checkpoint_seq(self.blocks, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = self.pooling(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        from ._features import feature_take_indices
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        x = self.stem_bn(self.act(self.stem_conv(x)))
        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x)
            if i in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        from ._features import feature_take_indices
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    """Reference uses nested Sequential indices
    (stem.0/2, blocks.N.0.fn.0/2, blocks.N.1/3)."""
    import re

    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        k = re.sub(r'^stem\.0\.', 'stem_conv.', k)
        k = re.sub(r'^stem\.2\.', 'stem_bn.', k)
        k = re.sub(r'^blocks\.(\d+)\.0\.fn\.0\.', r'blocks.\1.conv_dw.', k)
        k = re.sub(r'^blocks\.(\d+)\.0\.fn\.2\.', r'blocks.\1.bn1.', k)
        k = re.sub(r'^blocks\.(\d+)\.1\.', r'blocks.\1.conv_pw.', k)
        k = re.sub(r'^blocks\.(\d+)\.3\.', r'blocks.\1.bn2.', k)
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_convmixer(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        ConvMixer, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        **kwargs,
    )


def _cfg(url='', **kwargs):
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': None,
        'crop_pct': 0.96, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225), 'classifier': 'head',
        'first_conv': 'stem_conv', 'license': 'mit',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'convmixer_1536_20.in1k': _cfg(hf_hub_id='timm/'),
    'convmixer_768_32.in1k': _cfg(hf_hub_id='timm/'),
    'convmixer_1024_20_ks9_p14.in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def convmixer_1536_20(pretrained=False, **kwargs) -> ConvMixer:
    model_args = dict(dim=1536, depth=20, kernel_size=9, patch_size=7)
    return _create_convmixer('convmixer_1536_20', pretrained, **dict(model_args, **kwargs))


@register_model
def convmixer_768_32(pretrained=False, **kwargs) -> ConvMixer:
    model_args = dict(dim=768, depth=32, kernel_size=7, patch_size=7, act_layer='relu')
    return _create_convmixer('convmixer_768_32', pretrained, **dict(model_args, **kwargs))


@register_model
def convmixer_1024_20_ks9_p14(pretrained=False, **kwargs) -> ConvMixer:
    model_args = dict(dim=1024, depth=20, kernel_size=9, patch_size=14)
    return _create_convmixer('convmixer_1024_20_ks9_p14', pretrained, **dict(model_args, **kwargs))
