"""LeViT, TPU-native (reference: timm/models/levit.py:1-1152; Graham et al.
2021, 'LeViT: a Vision Transformer in ConvNet's Clothing').

Hybrid conv-stem + attention pyramid where every linear is fused with a
BatchNorm (train-time BN folds into the matmul at inference) and attention
adds a learned per-head relative bias gathered from a static index table.

TPU-first notes: the reference maintains parallel `levit_*` (linear, NLC) and
`levit_conv_*` (1×1 conv, NCHW) module trees purely for torch memory-layout
reasons. In NHWC/XLA a 1×1 conv IS a matmul, so one token implementation
serves both registries (checkpoints for either load through the same
converter). Attention bias indices are trace-time numpy constants; the
subsample downsample is a static strided slice on the token grid.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import nnx

from ..layers import (
    BatchNorm2d, Dropout, DropPath, get_act_fn, to_2tuple, to_ntuple,
    trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['Levit', 'LevitDistilled']


class ConvNorm(nnx.Module):
    """Conv (no bias) + BN, NHWC (reference levit.py:43-78)."""

    def __init__(self, in_chs, out_chs, kernel_size=1, stride=1, padding=0,
                 groups=1, bn_weight_init=1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.linear = nnx.Conv(
            in_chs, out_chs, kernel_size=(kernel_size, kernel_size), strides=stride,
            padding=[(padding, padding), (padding, padding)], feature_group_count=groups,
            use_bias=False, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_chs, rngs=rngs)
        if bn_weight_init != 1.0:
            self.bn.scale[...] = jnp.full_like(self.bn.scale[...], bn_weight_init)

    def __call__(self, x):
        return self.bn(self.linear(x))


class LinearNorm(nnx.Module):
    """Linear (no bias) + BN over (B*N) tokens (reference levit.py:81-110)."""

    def __init__(self, in_features, out_features, bn_weight_init=1.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.linear = nnx.Linear(
            in_features, out_features, use_bias=False, kernel_init=trunc_normal_(std=0.02),
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_features, rngs=rngs)
        if bn_weight_init != 1.0:
            self.bn.scale[...] = jnp.full_like(self.bn.scale[...], bn_weight_init)

    def __call__(self, x):
        x = self.linear(x)
        B, N, C = x.shape
        return self.bn(x.reshape(B, N, 1, C)).reshape(B, N, C)


class NormLinear(nnx.Module):
    """BN + dropout + linear classifier head (reference levit.py:113-151)."""

    def __init__(self, in_features, out_features, bias=True, std=0.02, drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.bn = BatchNorm2d(in_features, rngs=rngs)
        self.drop = Dropout(drop, rngs=rngs)
        self.linear = nnx.Linear(
            in_features, out_features, use_bias=bias, kernel_init=trunc_normal_(std=std),
            bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)

    def __call__(self, x):
        B, C = x.shape
        x = self.bn(x.reshape(B, 1, 1, C)).reshape(B, C)
        return self.linear(self.drop(x))


class Stem(nnx.Module):
    """Strided ConvNorm stack, s8 or s16 (reference levit.py:153-192)."""

    def __init__(self, in_chs, out_chs, act_layer, stem_type='s16',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.act = get_act_fn(act_layer)
        if stem_type == 's16':
            self.stride = 16
            chs = [out_chs // 8, out_chs // 4, out_chs // 2, out_chs]
        else:
            self.stride = 8
            chs = [out_chs // 4, out_chs // 2, out_chs]
        convs = []
        c_in = in_chs
        for c in chs:
            convs.append(ConvNorm(c_in, c, 3, stride=2, padding=1, **kw))
            c_in = c
        self.convs = nnx.List(convs)

    def __call__(self, x):
        for i, conv in enumerate(self.convs):
            if i:
                x = self.act(x)
            x = conv(x)
        return x


def _attention_bias_idxs(resolution: Tuple[int, int], stride: int = 1) -> np.ndarray:
    """Static (N_q, N_k) index into the per-head bias table (reference
    levit.py:286-296, 395-407)."""
    H, W = resolution
    k_pos = np.stack(np.meshgrid(np.arange(H), np.arange(W), indexing='ij')).reshape(2, -1)
    q_pos = np.stack(np.meshgrid(
        np.arange(0, H, step=stride), np.arange(0, W, step=stride), indexing='ij')).reshape(2, -1)
    rel = np.abs(q_pos[:, :, None] - k_pos[:, None, :])
    return rel[0] * W + rel[1]


class LevitAttention(nnx.Module):
    """MHSA w/ learned relative bias table (reference levit.py:219-328)."""

    def __init__(self, dim, key_dim, num_heads=8, attn_ratio=4.0, resolution=14,
                 act_layer='hard_swish',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        resolution = to_2tuple(resolution)
        self.num_heads = num_heads
        self.scale = key_dim ** -0.5
        self.key_dim = key_dim
        self.val_dim = int(attn_ratio * key_dim)
        self.val_attn_dim = self.val_dim * num_heads

        self.qkv = LinearNorm(dim, self.val_attn_dim + key_dim * num_heads * 2, **kw)
        self.proj_act = get_act_fn(act_layer)
        self.proj_ln = LinearNorm(self.val_attn_dim, dim, bn_weight_init=0, **kw)

        N = resolution[0] * resolution[1]
        self.attention_biases = nnx.Param(jnp.zeros((num_heads, N), param_dtype))
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(resolution)))

    def _bias(self):
        return self.attention_biases[...][:, self._bias_idxs[...]]  # (H, N, N)

    def __call__(self, x):
        B, N, C = x.shape
        qkv = self.qkv(x).reshape(B, N, self.num_heads, -1)
        q, k, v = jnp.split(qkv, [self.key_dim, self.key_dim * 2], axis=3)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        attn = jnp.einsum('bhnd,bhmd->bhnm', q, k) * self.scale + self._bias().astype(q.dtype)
        attn = jax.nn.softmax(attn, axis=-1)
        x = jnp.einsum('bhnm,bhmd->bhnd', attn, v)
        x = x.transpose(0, 2, 1, 3).reshape(B, N, self.val_attn_dim)
        return self.proj_ln(self.proj_act(x))


class LevitAttentionDownsample(nnx.Module):
    """Attention with stride-subsampled queries (reference levit.py:330-459)."""

    def __init__(self, in_dim, out_dim, key_dim, num_heads=8, attn_ratio=2.0,
                 stride=2, resolution=14, act_layer='hard_swish',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        resolution = to_2tuple(resolution)
        self.resolution = resolution
        self.stride = stride
        self.num_heads = num_heads
        self.key_dim = key_dim
        self.val_dim = int(attn_ratio * key_dim)
        self.val_attn_dim = self.val_dim * num_heads
        self.scale = key_dim ** -0.5

        self.kv = LinearNorm(in_dim, self.val_attn_dim + key_dim * num_heads, **kw)
        self.q_ln = LinearNorm(in_dim, key_dim * num_heads, **kw)
        self.proj_act = get_act_fn(act_layer)
        self.proj_ln = LinearNorm(self.val_attn_dim, out_dim, **kw)

        N_k = resolution[0] * resolution[1]
        self.attention_biases = nnx.Param(jnp.zeros((num_heads, N_k), param_dtype))
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(resolution, stride=stride)))

    def _bias(self):
        return self.attention_biases[...][:, self._bias_idxs[...]]  # (H, N_q, N_k)

    def __call__(self, x):
        B, N, C = x.shape
        H, W = self.resolution
        kv = self.kv(x).reshape(B, N, self.num_heads, -1)
        k, v = jnp.split(kv, [self.key_dim], axis=3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        # subsample queries on the static token grid
        xq = x.reshape(B, H, W, C)[:, ::self.stride, ::self.stride].reshape(B, -1, C)
        q = self.q_ln(xq).reshape(B, -1, self.num_heads, self.key_dim).transpose(0, 2, 1, 3)
        attn = jnp.einsum('bhnd,bhmd->bhnm', q, k) * self.scale + self._bias().astype(q.dtype)
        attn = jax.nn.softmax(attn, axis=-1)
        x = jnp.einsum('bhnm,bhmd->bhnd', attn, v)
        x = x.transpose(0, 2, 1, 3).reshape(B, -1, self.val_attn_dim)
        return self.proj_ln(self.proj_act(x))


class LevitMlp(nnx.Module):
    """LinearNorm MLP (reference levit.py:461-491)."""

    def __init__(self, in_features, hidden_features=None, out_features=None,
                 act_layer='hard_swish', drop=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        self.ln1 = LinearNorm(in_features, hidden_features, **kw)
        self.act = get_act_fn(act_layer)
        self.drop = Dropout(drop, rngs=rngs)
        self.ln2 = LinearNorm(hidden_features, out_features, bn_weight_init=0, **kw)

    def __call__(self, x):
        return self.ln2(self.drop(self.act(self.ln1(x))))


class LevitDownsample(nnx.Module):
    """Attention downsample + residual MLP (reference levit.py:494-541)."""

    def __init__(self, in_dim, out_dim, key_dim, num_heads=8, attn_ratio=4.0,
                 mlp_ratio=2.0, act_layer='hard_swish', attn_act_layer=None,
                 resolution=14, drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn_downsample = LevitAttentionDownsample(
            in_dim, out_dim, key_dim=key_dim, num_heads=num_heads, attn_ratio=attn_ratio,
            act_layer=attn_act_layer or act_layer, resolution=resolution, **kw)
        self.mlp = LevitMlp(out_dim, int(out_dim * mlp_ratio), act_layer=act_layer, **kw)
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = self.attn_downsample(x)
        return x + self.drop_path(self.mlp(x))


class LevitBlock(nnx.Module):
    """Attention + MLP residual block (reference levit.py:544-589)."""

    def __init__(self, dim, key_dim, num_heads=8, attn_ratio=4.0, mlp_ratio=2.0,
                 resolution=14, act_layer='hard_swish', attn_act_layer=None, drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.attn = LevitAttention(
            dim, key_dim, num_heads=num_heads, attn_ratio=attn_ratio,
            resolution=resolution, act_layer=attn_act_layer or act_layer, **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.mlp = LevitMlp(dim, int(dim * mlp_ratio), act_layer=act_layer, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

    def __call__(self, x):
        x = x + self.drop_path1(self.attn(x))
        x = x + self.drop_path2(self.mlp(x))
        return x


class LevitStage(nnx.Module):
    """Optional downsample + block stack (reference levit.py:591-655)."""

    def __init__(self, in_dim, out_dim, key_dim, depth=4, num_heads=8, attn_ratio=4.0,
                 mlp_ratio=4.0, act_layer='hard_swish', attn_act_layer=None,
                 resolution=14, downsample='', drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        resolution = to_2tuple(resolution)
        if downsample:
            self.downsample = LevitDownsample(
                in_dim, out_dim, key_dim=key_dim, num_heads=in_dim // key_dim,
                attn_ratio=4.0, mlp_ratio=2.0, act_layer=act_layer,
                attn_act_layer=attn_act_layer, resolution=resolution, drop_path=drop_path, **kw)
            resolution = tuple((r - 1) // 2 + 1 for r in resolution)
        else:
            assert in_dim == out_dim
            self.downsample = None
        self.resolution = resolution
        self.blocks = nnx.List([
            LevitBlock(
                out_dim, key_dim, num_heads=num_heads, attn_ratio=attn_ratio,
                mlp_ratio=mlp_ratio, act_layer=act_layer, attn_act_layer=attn_act_layer,
                resolution=resolution, drop_path=drop_path, **kw)
            for _ in range(depth)
        ])

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        for blk in self.blocks:
            x = blk(x)
        return x


class Levit(nnx.Module):
    """LeViT with the reference's model contract (reference levit.py:657-873)."""

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            in_chans: int = 3,
            num_classes: int = 1000,
            embed_dim: Tuple[int, ...] = (192,),
            key_dim: int = 64,
            depth: Tuple[int, ...] = (12,),
            num_heads: Union[int, Tuple[int, ...]] = (3,),
            attn_ratio: Union[float, Tuple[float, ...]] = 2.0,
            mlp_ratio: Union[float, Tuple[float, ...]] = 2.0,
            stem_type: str = 's16',
            down_op: str = 'subsample',
            act_layer: str = 'hard_swish',
            attn_act_layer: Optional[str] = None,
            use_conv: bool = False,  # accepted for cfg parity; NHWC path is identical
            global_pool: str = 'avg',
            drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = embed_dim[-1]
        self.embed_dim = embed_dim
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        self.feature_info = []

        num_stages = len(embed_dim)
        assert len(depth) == num_stages
        num_heads = to_ntuple(num_stages)(num_heads)
        attn_ratio = to_ntuple(num_stages)(attn_ratio)
        mlp_ratio = to_ntuple(num_stages)(mlp_ratio)

        self.stem = Stem(in_chans, embed_dim[0], act_layer=act_layer, stem_type=stem_type, **kw)
        stride = self.stem.stride
        resolution = tuple(i // stride for i in to_2tuple(img_size))

        in_dim = embed_dim[0]
        stages = []
        for i in range(num_stages):
            stage_stride = 2 if i > 0 else 1
            stages.append(LevitStage(
                in_dim, embed_dim[i], key_dim, depth=depth[i], num_heads=num_heads[i],
                attn_ratio=attn_ratio[i], mlp_ratio=mlp_ratio[i], act_layer=act_layer,
                attn_act_layer=attn_act_layer, resolution=resolution,
                downsample=down_op if stage_stride == 2 else '', drop_path=drop_path_rate, **kw))
            stride *= stage_stride
            resolution = tuple((r - 1) // stage_stride + 1 for r in resolution)
            self.feature_info += [dict(num_chs=embed_dim[i], reduction=stride, module=f'stages.{i}')]
            in_dim = embed_dim[i]
        self.stages = nnx.List(stages)

        self.head = NormLinear(embed_dim[-1], num_classes, drop=drop_rate, **kw) \
            if num_classes > 0 else None
        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self):
        return {'attention_biases'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^stem',
            blocks=[(r'^stages\.(\d+)', None)],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = NormLinear(
            self.num_features, num_classes, drop=self.drop_rate,
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        from ._manipulate import checkpoint_seq
        x = self.stem(x)
        B, H, W, C = x.shape
        x = x.reshape(B, H * W, C)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.stages, x)
        else:
            for stage in self.stages:
                x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=1)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        x = self.stem(x)
        B, H, W, C = x.shape
        x = x.reshape(B, H * W, C)
        intermediates = []
        stages = self.stages if not stop_early else list(self.stages)[:max_index + 1]
        for i, stage in enumerate(stages):
            x = stage(x)
            if i in take_indices:
                h, w = stage.resolution
                intermediates.append(x.reshape(B, h, w, -1))
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        self.num_features = self.stages[-1].blocks[-1].mlp.ln2.linear.out_features \
            if self.stages[-1].blocks else self.num_features
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


class LevitDistilled(Levit):
    """LeViT w/ distillation head (reference levit.py:875-910)."""

    def __init__(self, *args, rngs: nnx.Rngs, **kwargs):
        super().__init__(*args, rngs=rngs, **kwargs)
        self.head_dist = NormLinear(
            self.num_features, self.num_classes, dtype=self._dtype,
            param_dtype=self._param_dtype, rngs=rngs) if self.num_classes > 0 else None
        self.distilled_training = False

    def get_classifier(self):
        return self.head, self.head_dist

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs)
        self.head = NormLinear(self.num_features, num_classes, drop=self.drop_rate, **kw) \
            if num_classes > 0 else None
        self.head_dist = NormLinear(self.num_features, num_classes, **kw) if num_classes > 0 else None

    def set_distilled_training(self, enable: bool = True):
        self.distilled_training = enable

    def forward_head(self, x, pre_logits: bool = False):
        if self.global_pool == 'avg':
            x = x.mean(axis=1)
        if pre_logits or self.head is None:
            return x
        out, out_dist = self.head(x), self.head_dist(x)
        if self.distilled_training and not self.head.drop.deterministic:
            return out, out_dist
        return (out + out_dist) / 2


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    import re
    if 'model' in state_dict:
        state_dict = state_dict['model']
    out = {}
    for k, v in state_dict.items():
        if 'attention_bias_idxs' in k:
            continue
        # torch stem Sequential conv{1..4} → convs.{0..3}
        m = re.match(r'^stem\.conv(\d)\.(.*)$', k)
        if m:
            k = f'stem.convs.{int(m.group(1)) - 1}.{m.group(2)}'
        # torch proj Sequential ('act','ln') and q Sequential ('down','ln')
        k = k.replace('.proj.ln.', '.proj_ln.').replace('.q.ln.', '.q_ln.')
        out[k] = v
    return convert_torch_state_dict(out, model)


model_cfgs = dict(
    levit_128s=dict(embed_dim=(128, 256, 384), key_dim=16, num_heads=(4, 6, 8), depth=(2, 3, 4)),
    levit_128=dict(embed_dim=(128, 256, 384), key_dim=16, num_heads=(4, 8, 12), depth=(4, 4, 4)),
    levit_192=dict(embed_dim=(192, 288, 384), key_dim=32, num_heads=(3, 5, 6), depth=(4, 4, 4)),
    levit_256=dict(embed_dim=(256, 384, 512), key_dim=32, num_heads=(4, 6, 8), depth=(4, 4, 4)),
    levit_384=dict(embed_dim=(384, 512, 768), key_dim=32, num_heads=(6, 9, 12), depth=(4, 4, 4)),
    levit_384_s8=dict(embed_dim=(384, 512, 768), key_dim=32, num_heads=(6, 9, 12), depth=(4, 4, 4),
                      act_layer='silu', stem_type='s8'),
    levit_512_s8=dict(embed_dim=(512, 640, 896), key_dim=64, num_heads=(8, 10, 14), depth=(4, 4, 4),
                      act_layer='silu', stem_type='s8'),
    levit_512=dict(embed_dim=(512, 768, 1024), key_dim=64, num_heads=(8, 12, 16), depth=(4, 4, 4),
                   act_layer='silu'),
    levit_256d=dict(embed_dim=(256, 384, 512), key_dim=32, num_heads=(4, 6, 8), depth=(4, 8, 6),
                    act_layer='silu'),
    levit_512d=dict(embed_dim=(512, 640, 768), key_dim=64, num_heads=(8, 10, 12), depth=(4, 8, 6),
                    act_layer='silu'),
    test_levit=dict(embed_dim=(32, 48), key_dim=16, num_heads=(2, 3), depth=(1, 1), stem_type='s8'),
)


def create_levit(variant, cfg_variant=None, pretrained=False, distilled=True, **kwargs):
    out_indices = kwargs.pop('out_indices', (0, 1, 2))
    if cfg_variant is None:
        if variant in model_cfgs:
            cfg_variant = variant
        elif '_conv' in variant:
            cfg_variant = variant.replace('_conv', '')
    model_cfg = dict(model_cfgs[cfg_variant], **kwargs)
    return build_model_with_cfg(
        LevitDistilled if distilled else Levit,
        variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **model_cfg,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.convs.0.linear',
        'classifier': ('head.linear', 'head_dist.linear'),
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'levit_128s.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'levit_128.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'levit_192.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'levit_256.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'levit_384.fb_dist_in1k': _cfg(hf_hub_id='timm/'),
    'levit_conv_128s.fb_dist_in1k': _cfg(hf_hub_id='timm/', pool_size=(4, 4)),
    'levit_conv_128.fb_dist_in1k': _cfg(hf_hub_id='timm/', pool_size=(4, 4)),
    'levit_conv_192.fb_dist_in1k': _cfg(hf_hub_id='timm/', pool_size=(4, 4)),
    'levit_conv_256.fb_dist_in1k': _cfg(hf_hub_id='timm/', pool_size=(4, 4)),
    'levit_conv_384.fb_dist_in1k': _cfg(hf_hub_id='timm/', pool_size=(4, 4)),
    'levit_384_s8.untrained': _cfg(classifier='head.linear'),
    'levit_512_s8.untrained': _cfg(classifier='head.linear'),
    'levit_512.untrained': _cfg(classifier='head.linear'),
    'levit_256d.untrained': _cfg(classifier='head.linear'),
    'levit_512d.untrained': _cfg(classifier='head.linear'),
    'levit_conv_384_s8.untrained': _cfg(classifier='head.linear'),
    'levit_conv_512_s8.untrained': _cfg(classifier='head.linear'),
    'levit_conv_512.untrained': _cfg(classifier='head.linear'),
    'levit_conv_256d.untrained': _cfg(classifier='head.linear'),
    'levit_conv_512d.untrained': _cfg(classifier='head.linear'),
    'test_levit.untrained': _cfg(input_size=(3, 96, 96), classifier='head.linear'),
})


@register_model
def levit_128s(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_128s', pretrained=pretrained, **kwargs)


@register_model
def levit_128(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_128', pretrained=pretrained, **kwargs)


@register_model
def levit_192(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_192', pretrained=pretrained, **kwargs)


@register_model
def levit_256(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_256', pretrained=pretrained, **kwargs)


@register_model
def levit_384(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_384', pretrained=pretrained, **kwargs)


@register_model
def levit_384_s8(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_384_s8', pretrained=pretrained, **kwargs)


@register_model
def levit_512_s8(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_512_s8', pretrained=pretrained, distilled=False, **kwargs)


@register_model
def levit_512(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_512', pretrained=pretrained, distilled=False, **kwargs)


@register_model
def levit_256d(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_256d', pretrained=pretrained, distilled=False, **kwargs)


@register_model
def levit_512d(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_512d', pretrained=pretrained, distilled=False, **kwargs)


@register_model
def levit_conv_128s(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_128s', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_128(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_128', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_192(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_192', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_256(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_256', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_384(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_384', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_384_s8(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_384_s8', pretrained=pretrained, use_conv=True, **kwargs)


@register_model
def levit_conv_512_s8(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_512_s8', pretrained=pretrained, use_conv=True, distilled=False, **kwargs)


@register_model
def levit_conv_512(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_512', pretrained=pretrained, use_conv=True, distilled=False, **kwargs)


@register_model
def levit_conv_256d(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_256d', pretrained=pretrained, use_conv=True, distilled=False, **kwargs)


@register_model
def levit_conv_512d(pretrained=False, **kwargs) -> Levit:
    return create_levit('levit_conv_512d', pretrained=pretrained, use_conv=True, distilled=False, **kwargs)


@register_model
def test_levit(pretrained=False, **kwargs) -> Levit:
    return create_levit('test_levit', pretrained=pretrained, distilled=False, **kwargs)
