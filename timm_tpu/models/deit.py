"""DeiT — data-efficient ViT w/ distillation token
(reference: timm/models/deit.py:1-423).

VisionTransformerDistilled adds a dist_token + separate head; eval-mode
forward averages the two heads (reference deit.py forward_head).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax.numpy as jnp
from flax import nnx

from ..layers import trunc_normal_, zeros_
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .vision_transformer import VisionTransformer

__all__ = ['VisionTransformerDistilled']


class VisionTransformerDistilled(VisionTransformer):
    """ViT + distillation token (reference deit.py VisionTransformerDistilled)."""

    def __init__(self, *args, rngs: nnx.Rngs, **kwargs):
        # the distillation-token design requires a class token + token pooling
        caller_pool = kwargs.pop('global_pool', 'token')
        assert caller_pool in ('token',), 'VisionTransformerDistilled requires token pooling'
        kwargs.pop('class_token', None)
        super().__init__(*args, rngs=rngs, class_token=True, global_pool='token', **kwargs)
        assert self.global_pool == 'token'

        self.num_prefix_tokens += 1
        self.dist_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, 1, self.embed_dim), self._param_dtype or jnp.float32))
        # pos embed needs the extra token slot: rebuild
        num_patches = self.patch_embed.num_patches
        embed_len = num_patches if self.no_embed_class else num_patches + self.num_prefix_tokens
        self.pos_embed = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, embed_len, self.embed_dim),
                                    self._param_dtype or jnp.float32))
        self.head_dist = nnx.Linear(
            self.embed_dim, self.num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
            dtype=self._dtype, param_dtype=self._param_dtype or jnp.float32, rngs=rngs,
        ) if self.num_classes > 0 else None
        self.distilled_training = False  # toggled by the distillation task

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|dist_token',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def no_weight_decay(self) -> set:
        return super().no_weight_decay() | {'dist_token'}

    def get_classifier(self):
        return self.head, self.head_dist

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        super().reset_classifier(num_classes, global_pool, rngs=rngs)
        self.head_dist = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype or jnp.float32, rngs=rngs,
        ) if num_classes > 0 else None

    def set_distilled_training(self, enable: bool = True):
        self.distilled_training = enable

    def _pos_embed(self, x, grid_size=None, pad_tokens_to=None):
        B = x.shape[0]
        pos_embed = self.pos_embed[...].astype(x.dtype) if self.pos_embed is not None else None
        to_cat = [
            jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1])),
            jnp.broadcast_to(self.dist_token[...].astype(x.dtype), (B, 1, x.shape[-1])),
        ]
        if self.no_embed_class:
            if pos_embed is not None:
                x = x + pos_embed
            x = jnp.concatenate(to_cat + [x], axis=1)
        else:
            x = jnp.concatenate(to_cat + [x], axis=1)
            if pos_embed is not None:
                x = x + pos_embed
        return self._pad_token_seq(self.pos_drop(x), pad_tokens_to)

    def forward_head(self, x, pre_logits: bool = False):
        x_cls, x_dist = x[:, 0], x[:, 1]
        if pre_logits or self.head is None or self.head_dist is None:
            return (x_cls + x_dist) / 2
        x_cls = self.head(x_cls)
        x_dist = self.head_dist(x_dist)
        if self.distilled_training:
            return x_cls, x_dist  # distillation task consumes both
        return (x_cls + x_dist) / 2


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.875,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.485, 0.456, 0.406),
        'std': (0.229, 0.224, 0.225),
        'first_conv': 'patch_embed.proj',
        'classifier': ('head', 'head_dist'),
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'deit_tiny_distilled_patch16_224.fb_in1k': _cfg(hf_hub_id='timm/'),
    'deit_small_distilled_patch16_224.fb_in1k': _cfg(hf_hub_id='timm/'),
    'deit_base_distilled_patch16_224.fb_in1k': _cfg(hf_hub_id='timm/'),
    'deit3_small_patch16_224.fb_in22k_ft_in1k': _cfg(hf_hub_id='timm/', classifier='head'),
    'deit3_base_patch16_224.fb_in22k_ft_in1k': _cfg(hf_hub_id='timm/', classifier='head'),
})


def _create_deit(variant: str, pretrained: bool = False, distilled: bool = False, **kwargs):
    from ._torch_convert import convert_torch_state_dict
    model_cls = VisionTransformerDistilled if distilled else VisionTransformer
    return build_model_with_cfg(
        model_cls, variant, pretrained,
        pretrained_filter_fn=convert_torch_state_dict,
        **kwargs,
    )


@register_model
def deit_tiny_distilled_patch16_224(pretrained=False, **kwargs):
    model_args = dict(patch_size=16, embed_dim=192, depth=12, num_heads=3)
    return _create_deit('deit_tiny_distilled_patch16_224', pretrained, distilled=True, **dict(model_args, **kwargs))


@register_model
def deit_small_distilled_patch16_224(pretrained=False, **kwargs):
    model_args = dict(patch_size=16, embed_dim=384, depth=12, num_heads=6)
    return _create_deit('deit_small_distilled_patch16_224', pretrained, distilled=True, **dict(model_args, **kwargs))


@register_model
def deit_base_distilled_patch16_224(pretrained=False, **kwargs):
    model_args = dict(patch_size=16, embed_dim=768, depth=12, num_heads=12)
    return _create_deit('deit_base_distilled_patch16_224', pretrained, distilled=True, **dict(model_args, **kwargs))


@register_model
def deit3_small_patch16_224(pretrained=False, **kwargs):
    """DeiT-III: no dist token, LayerScale + no pos-embed class token."""
    model_args = dict(
        patch_size=16, embed_dim=384, depth=12, num_heads=6, no_embed_class=True, init_values=1e-6)
    return _create_deit('deit3_small_patch16_224', pretrained, **dict(model_args, **kwargs))


@register_model
def deit3_base_patch16_224(pretrained=False, **kwargs):
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, no_embed_class=True, init_values=1e-6)
    return _create_deit('deit3_base_patch16_224', pretrained, **dict(model_args, **kwargs))
