"""TinyViT — fast-pretraining-distillation small ViTs (NHWC / nnx).

Re-implements reference timm/models/tiny_vit.py:1-880 (TinyVit): a conv
stem + MBConv stage followed by three windowed-attention stages with
LeViT-style cached relative attention biases, depthwise local conv between
attention and MLP, and a NormMlp classifier head.

TPU notes: the whole network stays NHWC (the reference permutes NCHW↔NHWC at
every stage boundary; here there is nothing to permute). Window partitioning
is a static reshape/transpose chain, the attention bias is a static gather
from a per-resolution index table (same machinery as levit.py), and window
padding sizes are compile-time constants, so every attention runs as one
batched MXU matmul over (B·windows) with no dynamic shapes.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import nnx

from timm_tpu.data.constants import IMAGENET_DEFAULT_MEAN, IMAGENET_DEFAULT_STD
from ..layers import (
    BatchNorm2d, Dropout, DropPath, LayerNorm, LayerNorm2d, NormMlpClassifierHead,
    calculate_drop_path_rates, get_act_fn, to_2tuple, trunc_normal_, zeros_,
)
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model
from .levit import _attention_bias_idxs

__all__ = ['TinyVit']


class ConvNorm(nnx.Module):
    """Conv (named ``conv``) + BN (reference tiny_vit.py:29-62)."""

    def __init__(self, in_chs, out_chs, ks=1, stride=1, pad=0, dilation=1, groups=1,
                 bn_weight_init=1.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.conv = nnx.Conv(
            in_chs, out_chs, kernel_size=(ks, ks), strides=stride,
            padding=[(pad, pad), (pad, pad)], kernel_dilation=(dilation, dilation),
            feature_group_count=groups, use_bias=False,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.bn = BatchNorm2d(out_chs, rngs=rngs)
        if bn_weight_init != 1.0:
            self.bn.scale[...] = jnp.full_like(self.bn.scale[...], bn_weight_init)

    def __call__(self, x):
        return self.bn(self.conv(x))


class PatchEmbed(nnx.Module):
    """Two strided 3x3 ConvNorms, stride 4 (reference tiny_vit.py:65-86)."""

    def __init__(self, in_chs, out_chs, act_layer, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.stride = 4
        self.conv1 = ConvNorm(in_chs, out_chs // 2, 3, 2, 1, **kw)
        self.act = get_act_fn(act_layer)
        self.conv2 = ConvNorm(out_chs // 2, out_chs, 3, 2, 1, **kw)

    def __call__(self, x):
        return self.conv2(self.act(self.conv1(x)))


class MBConv(nnx.Module):
    """Inverted residual with post-add act (reference tiny_vit.py:89-123)."""

    def __init__(self, in_chs, out_chs, expand_ratio, act_layer, drop_path,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        mid_chs = int(in_chs * expand_ratio)
        self.conv1 = ConvNorm(in_chs, mid_chs, ks=1, **kw)
        self.act = get_act_fn(act_layer)
        self.conv2 = ConvNorm(mid_chs, mid_chs, ks=3, stride=1, pad=1, groups=mid_chs, **kw)
        self.conv3 = ConvNorm(mid_chs, out_chs, ks=1, bn_weight_init=0.0, **kw)
        self.drop_path = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None

    def __call__(self, x):
        shortcut = x
        x = self.act(self.conv1(x))
        x = self.act(self.conv2(x))
        x = self.conv3(x)
        if self.drop_path is not None:
            x = self.drop_path(x)
        return self.act(x + shortcut)


class PatchMerging(nnx.Module):
    """1x1 expand → dw 3x3 s2 → 1x1 (reference tiny_vit.py:126-149)."""

    def __init__(self, dim, out_dim, act_layer, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.conv1 = ConvNorm(dim, out_dim, 1, 1, 0, **kw)
        self.act = get_act_fn(act_layer)
        self.conv2 = ConvNorm(out_dim, out_dim, 3, 2, 1, groups=out_dim, **kw)
        self.conv3 = ConvNorm(out_dim, out_dim, 1, 1, 0, **kw)

    def __call__(self, x):
        return self.conv3(self.act(self.conv2(self.act(self.conv1(x)))))


class NormMlp(nnx.Module):
    """LN → fc1 → act → drop → fc2 → drop (reference tiny_vit.py:180-212)."""

    def __init__(self, in_features, hidden_features=None, out_features=None,
                 act_layer='gelu', drop=0.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        out_features = out_features or in_features
        hidden_features = hidden_features or in_features
        linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                         bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = LayerNorm(in_features, eps=1e-5, rngs=rngs)
        self.fc1 = linear(in_features, hidden_features)
        self.act = get_act_fn(act_layer)
        self.drop1 = Dropout(drop, rngs=rngs)
        self.fc2 = linear(hidden_features, out_features)
        self.drop2 = Dropout(drop, rngs=rngs)

    def __call__(self, x):
        x = self.drop1(self.act(self.fc1(self.norm(x))))
        return self.drop2(self.fc2(x))


class TinyVitAttention(nnx.Module):
    """Pre-norm multi-head attention with LeViT-style per-resolution relative
    bias table gathered by a static index (reference tiny_vit.py:215-320).
    The bias gather is a compile-time-constant indexed lookup — XLA folds it
    into the attention logits add."""

    def __init__(self, dim, key_dim, num_heads=8, attn_ratio=4, resolution=(14, 14),
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.num_heads = num_heads
        self.scale = key_dim ** -0.5
        self.key_dim = key_dim
        self.val_dim = int(attn_ratio * key_dim)
        self.out_dim = self.val_dim * num_heads
        self.resolution = to_2tuple(resolution)

        linear = partial(nnx.Linear, use_bias=True, kernel_init=trunc_normal_(std=0.02),
                         bias_init=zeros_, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.norm = LayerNorm(dim, eps=1e-5, rngs=rngs)
        self.qkv = linear(dim, num_heads * (self.val_dim + 2 * key_dim))
        self.proj = linear(self.out_dim, dim)

        num_offsets = self.resolution[0] * self.resolution[1]
        self.attention_biases = nnx.Param(jnp.zeros((num_heads, num_offsets), param_dtype))
        # nnx.Variable: raw array attrs break nnx graph traversal on older flax
        self._bias_idxs = nnx.Variable(jnp.asarray(_attention_bias_idxs(self.resolution)))

    def __call__(self, x):
        B, N, _ = x.shape
        bias = self.attention_biases[...][:, self._bias_idxs[...]]  # (H, N, N)
        x = self.norm(x)
        qkv = self.qkv(x).reshape(B, N, self.num_heads, -1)
        q, k, v = jnp.split(qkv, [self.key_dim, 2 * self.key_dim], axis=3)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        attn = (q * self.scale) @ k.transpose(0, 1, 3, 2) + bias
        attn = jax.nn.softmax(attn, axis=-1)
        x = (attn @ v).transpose(0, 2, 1, 3).reshape(B, N, self.out_dim)
        return self.proj(x)


class TinyVitBlock(nnx.Module):
    """Windowed attention + dw local conv + NormMlp, all NHWC
    (reference tiny_vit.py:323-437)."""

    def __init__(self, dim, num_heads, window_size=7, mlp_ratio=4., drop=0.,
                 drop_path=0., local_conv_size=3, act_layer='gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.dim = dim
        self.num_heads = num_heads
        assert window_size > 0 and dim % num_heads == 0
        self.window_size = window_size
        head_dim = dim // num_heads
        self.attn = TinyVitAttention(
            dim, head_dim, num_heads, attn_ratio=1,
            resolution=(window_size, window_size), **kw)
        self.drop_path1 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        self.mlp = NormMlp(dim, int(dim * mlp_ratio), act_layer=act_layer, drop=drop, **kw)
        self.drop_path2 = DropPath(drop_path, rngs=rngs) if drop_path > 0. else None
        pad = local_conv_size // 2
        self.local_conv = ConvNorm(dim, dim, ks=local_conv_size, stride=1, pad=pad, groups=dim, **kw)

    def __call__(self, x):
        B, H, W, C = x.shape
        ws = self.window_size
        shortcut = x
        if H == ws and W == ws:
            x = self.attn(x.reshape(B, H * W, C)).reshape(B, H, W, C)
        else:
            pad_b = (ws - H % ws) % ws
            pad_r = (ws - W % ws) % ws
            if pad_b or pad_r:
                x = jnp.pad(x, ((0, 0), (0, pad_b), (0, pad_r), (0, 0)))
            pH, pW = H + pad_b, W + pad_r
            nH, nW = pH // ws, pW // ws
            # window partition (static reshape/transpose)
            x = x.reshape(B, nH, ws, nW, ws, C).transpose(0, 1, 3, 2, 4, 5)
            x = x.reshape(B * nH * nW, ws * ws, C)
            x = self.attn(x)
            # window reverse
            x = x.reshape(B, nH, nW, ws, ws, C).transpose(0, 1, 3, 2, 4, 5).reshape(B, pH, pW, C)
            if pad_b or pad_r:
                x = x[:, :H, :W]
        x = shortcut + (self.drop_path1(x) if self.drop_path1 is not None else x)

        x = self.local_conv(x)
        x = x.reshape(B, H * W, C)
        y = self.mlp(x)
        x = x + (self.drop_path2(y) if self.drop_path2 is not None else y)
        return x.reshape(B, H, W, C)


class ConvLayer(nnx.Module):
    """Stage of MBConvs (reference tiny_vit.py:152-177)."""

    def __init__(self, dim, depth, act_layer, drop_path=0., conv_expand_ratio=4.,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.downsample = None
        self.blocks = nnx.List([
            MBConv(dim, dim, conv_expand_ratio, act_layer,
                   drop_path[i] if isinstance(drop_path, (list, tuple)) else drop_path, **kw)
            for i in range(depth)])
        self.grad_checkpointing = False

    def __call__(self, x):
        remat_blk = nnx.remat(MBConv.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            x = remat_blk(blk, x) if remat_blk is not None else blk(x)
        return x


class TinyVitStage(nnx.Module):
    """PatchMerging downsample + TinyVitBlocks (reference tiny_vit.py:440-505)."""

    def __init__(self, dim, out_dim, depth, num_heads, window_size, mlp_ratio=4.,
                 drop=0., drop_path=0., downsample=None, local_conv_size=3, act_layer='gelu',
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.out_dim = out_dim
        if downsample is not None:
            self.downsample = downsample(dim=dim, out_dim=out_dim, act_layer=act_layer, **kw)
        else:
            assert dim == out_dim
            self.downsample = None
        self.blocks = nnx.List([
            TinyVitBlock(
                dim=out_dim, num_heads=num_heads, window_size=window_size,
                mlp_ratio=mlp_ratio, drop=drop,
                drop_path=drop_path[i] if isinstance(drop_path, (list, tuple)) else drop_path,
                local_conv_size=local_conv_size, act_layer=act_layer, **kw)
            for i in range(depth)])
        self.grad_checkpointing = False

    def __call__(self, x):
        if self.downsample is not None:
            x = self.downsample(x)
        remat_blk = nnx.remat(TinyVitBlock.__call__) if self.grad_checkpointing else None
        for blk in self.blocks:
            x = remat_blk(blk, x) if remat_blk is not None else blk(x)
        return x


class TinyVit(nnx.Module):
    """TinyViT (reference tiny_vit.py:508-716)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dims: Tuple[int, ...] = (96, 192, 384, 768),
            depths: Tuple[int, ...] = (2, 2, 6, 2),
            num_heads: Tuple[int, ...] = (3, 6, 12, 24),
            window_sizes: Tuple[int, ...] = (7, 7, 14, 7),
            mlp_ratio: float = 4.,
            drop_rate: float = 0.,
            drop_path_rate: float = 0.1,
            use_checkpoint: bool = False,
            mbconv_expand_ratio: float = 4.0,
            local_conv_size: int = 3,
            act_layer='gelu',
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: Optional[nnx.Rngs] = None,
    ):
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.num_classes = num_classes
        self.depths = depths
        self.num_stages = len(depths)
        self.mlp_ratio = mlp_ratio

        self.patch_embed = PatchEmbed(in_chans, embed_dims[0], act_layer, **kw)

        dpr = calculate_drop_path_rates(drop_path_rate, sum(depths))
        stages = []
        stride = self.patch_embed.stride
        prev_dim = embed_dims[0]
        self.feature_info = []
        for stage_idx in range(self.num_stages):
            if stage_idx == 0:
                stage = ConvLayer(
                    dim=prev_dim, depth=depths[0], act_layer=act_layer,
                    drop_path=dpr[:depths[0]], conv_expand_ratio=mbconv_expand_ratio, **kw)
            else:
                out_dim = embed_dims[stage_idx]
                stage = TinyVitStage(
                    dim=embed_dims[stage_idx - 1], out_dim=out_dim, depth=depths[stage_idx],
                    num_heads=num_heads[stage_idx], window_size=window_sizes[stage_idx],
                    mlp_ratio=mlp_ratio, drop=drop_rate,
                    drop_path=dpr[sum(depths[:stage_idx]):sum(depths[:stage_idx + 1])],
                    downsample=PatchMerging, local_conv_size=local_conv_size,
                    act_layer=act_layer, **kw)
                prev_dim = out_dim
                stride *= 2
            stages.append(stage)
            self.feature_info += [dict(num_chs=prev_dim, reduction=stride, module=f'stages.{stage_idx}')]
        self.stages = nnx.List(stages)

        self.num_features = self.head_hidden_size = embed_dims[-1]
        self.head = NormMlpClassifierHead(
            self.num_features, num_classes, pool_type=global_pool,
            norm_layer=partial(LayerNorm2d, eps=1e-5), **kw)
        if use_checkpoint:
            self.set_grad_checkpointing(True)

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'attention_biases'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^patch_embed',
            blocks=r'^stages\.(\d+)' if coarse else [
                (r'^stages\.(\d+).downsample', (0,)),
                (r'^stages\.(\d+)\.\w+\.(\d+)', None),
            ])

    def set_grad_checkpointing(self, enable: bool = True):
        for s in self.stages:
            s.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        for stage in self.stages:
            x = stage(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(self, x, indices=None, norm: bool = False,
                              stop_early: bool = False, output_fmt: str = 'NHWC',
                              intermediates_only: bool = False):
        assert output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        intermediates = []
        x = self.patch_embed(x)
        stages = self.stages if not stop_early else self.stages[:max_index + 1]
        for feat_idx, stage in enumerate(stages):
            x = stage(x)
            if feat_idx in take_indices:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.stages), indices)
        self.stages = nnx.List(list(self.stages)[:max_index + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._helpers import model_state_dict
    from ._torch_convert import convert_torch_state_dict
    if 'model' in state_dict:
        state_dict = state_dict['model']
    state_dict = {k: v for k, v in state_dict.items() if not k.endswith('attention_bias_idxs')}
    # Cross-resolution loading: bilinearly resize each attention-bias table to
    # the target window resolution (reference tiny_vit.py:719-730 via
    # resize_rel_pos_bias_table_levit). The offset table's insertion order is
    # row-major (dr * W + dc), so it reshapes to the (H, W) offset grid.
    target = model_state_dict(model)
    out = {}
    for k, v in state_dict.items():
        if 'attention_biases' in k and k in target and tuple(v.shape) != tuple(target[k].shape):
            import numpy as np
            nh, n_src = v.shape
            n_tgt = target[k].shape[1]
            r_src = int(round(n_src ** 0.5))
            r_tgt = int(round(n_tgt ** 0.5))
            grid = jnp.asarray(np.asarray(v), jnp.float32).reshape(nh, r_src, r_src)
            grid = jax.image.resize(grid, (nh, r_tgt, r_tgt), method='bilinear')
            v = np.asarray(grid.reshape(nh, n_tgt))
        out[k] = v
    return convert_torch_state_dict(out, model)


def _cfg(url: str = '', **kwargs):
    return {
        'url': url, 'num_classes': 1000,
        'mean': IMAGENET_DEFAULT_MEAN, 'std': IMAGENET_DEFAULT_STD,
        'first_conv': 'patch_embed.conv1.conv', 'classifier': 'head.fc',
        'pool_size': (7, 7), 'input_size': (3, 224, 224), 'crop_pct': 0.95,
        'license': 'apache-2.0',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'tiny_vit_5m_224.dist_in22k': _cfg(num_classes=21841),
    'tiny_vit_5m_224.dist_in22k_ft_in1k': _cfg(),
    'tiny_vit_5m_224.in1k': _cfg(),
    'tiny_vit_11m_224.dist_in22k': _cfg(num_classes=21841),
    'tiny_vit_11m_224.dist_in22k_ft_in1k': _cfg(),
    'tiny_vit_11m_224.in1k': _cfg(),
    'tiny_vit_21m_224.dist_in22k': _cfg(num_classes=21841),
    'tiny_vit_21m_224.dist_in22k_ft_in1k': _cfg(),
    'tiny_vit_21m_224.in1k': _cfg(),
    'tiny_vit_21m_384.dist_in22k_ft_in1k': _cfg(
        input_size=(3, 384, 384), pool_size=(12, 12), crop_pct=1.0),
    'tiny_vit_21m_512.dist_in22k_ft_in1k': _cfg(
        input_size=(3, 512, 512), pool_size=(16, 16), crop_pct=1.0, crop_mode='squash'),
})


def _create_tiny_vit(variant, pretrained=False, **kwargs):
    out_indices = kwargs.pop('out_indices', (0, 1, 2, 3))
    return build_model_with_cfg(
        TinyVit, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices, feature_cls='getter'),
        **kwargs,
    )


@register_model
def tiny_vit_5m_224(pretrained=False, **kwargs):
    model_kwargs = dict(
        embed_dims=(64, 128, 160, 320), depths=(2, 2, 6, 2),
        num_heads=(2, 4, 5, 10), window_sizes=(7, 7, 14, 7), drop_path_rate=0.0)
    return _create_tiny_vit('tiny_vit_5m_224', pretrained, **dict(model_kwargs, **kwargs))


@register_model
def tiny_vit_11m_224(pretrained=False, **kwargs):
    model_kwargs = dict(
        embed_dims=(64, 128, 256, 448), depths=(2, 2, 6, 2),
        num_heads=(2, 4, 8, 14), window_sizes=(7, 7, 14, 7), drop_path_rate=0.1)
    return _create_tiny_vit('tiny_vit_11m_224', pretrained, **dict(model_kwargs, **kwargs))


@register_model
def tiny_vit_21m_224(pretrained=False, **kwargs):
    model_kwargs = dict(
        embed_dims=(96, 192, 384, 576), depths=(2, 2, 6, 2),
        num_heads=(3, 6, 12, 18), window_sizes=(7, 7, 14, 7), drop_path_rate=0.2)
    return _create_tiny_vit('tiny_vit_21m_224', pretrained, **dict(model_kwargs, **kwargs))


@register_model
def tiny_vit_21m_384(pretrained=False, **kwargs):
    model_kwargs = dict(
        embed_dims=(96, 192, 384, 576), depths=(2, 2, 6, 2),
        num_heads=(3, 6, 12, 18), window_sizes=(12, 12, 24, 12), drop_path_rate=0.1)
    return _create_tiny_vit('tiny_vit_21m_384', pretrained, **dict(model_kwargs, **kwargs))


@register_model
def tiny_vit_21m_512(pretrained=False, **kwargs):
    model_kwargs = dict(
        embed_dims=(96, 192, 384, 576), depths=(2, 2, 6, 2),
        num_heads=(3, 6, 12, 18), window_sizes=(16, 16, 32, 16), drop_path_rate=0.1)
    return _create_tiny_vit('tiny_vit_21m_512', pretrained, **dict(model_kwargs, **kwargs))
