"""ReXNet, TPU-native NHWC
(reference: timm/models/rexnet.py:1-610; Han et al. 2020).

Linearly growing channel schedule over MBConv-style blocks with partial
residual adds (only the first in_chs channels are residual) — the channel
slice+concat is a static NHWC op XLA folds away.
"""
from __future__ import annotations

from functools import partial
from math import ceil
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    BatchNorm2d, ClassifierHead, ConvNormAct, SEModule, get_act_fn, make_divisible,
)
from ..layers.drop import DropPath
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._registry import generate_default_cfgs, register_model

__all__ = ['RexNet']

SEWithNorm = partial(SEModule, norm_layer=BatchNorm2d)


class LinearBottleneck(nnx.Module):
    """(reference rexnet.py:28-133)."""

    def __init__(self, in_chs, out_chs, stride, dilation=(1, 1), exp_ratio=1.0,
                 se_ratio=0.0, ch_div=1, act_layer='swish', dw_act_layer='relu6',
                 drop_path=0.0, *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        self.use_shortcut = stride == 1 and dilation[0] == dilation[1] and in_chs <= out_chs
        self.in_channels = in_chs
        self.out_channels = out_chs
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        if exp_ratio != 1.0:
            dw_chs = make_divisible(round(in_chs * exp_ratio), divisor=ch_div)
            self.conv_exp = ConvNormAct(in_chs, dw_chs, act_layer=act_layer, **kw)
        else:
            dw_chs = in_chs
            self.conv_exp = None
        self.conv_dw = ConvNormAct(
            dw_chs, dw_chs, kernel_size=3, stride=stride, dilation=dilation[0],
            groups=dw_chs, apply_act=False, **kw)
        if se_ratio > 0:
            self.se = SEWithNorm(
                dw_chs, rd_channels=make_divisible(int(dw_chs * se_ratio), ch_div), **kw)
        else:
            self.se = None
        self.act_dw = get_act_fn(dw_act_layer)
        self.conv_pwl = ConvNormAct(dw_chs, out_chs, 1, apply_act=False, **kw)
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def feat_channels(self, exp: bool = False) -> int:
        return self.out_channels

    def __call__(self, x):
        shortcut = x
        if self.conv_exp is not None:
            x = self.conv_exp(x)
        x = self.conv_dw(x)
        if self.se is not None:
            x = self.se(x)
        x = self.act_dw(x)
        x = self.conv_pwl(x)
        if self.use_shortcut:
            x = self.drop_path(x)
            # partial residual: only the leading in_chs channels add the input
            head = x[..., :self.in_channels] + shortcut
            x = jnp.concatenate([head, x[..., self.in_channels:]], axis=-1)
        return x


def _block_cfg(width_mult=1.0, depth_mult=1.0, initial_chs=16, final_chs=180,
               se_ratio=0.0, ch_div=1):
    """(reference rexnet.py:136-173)."""
    layers = [1, 2, 2, 3, 3, 5]
    strides = [1, 2, 2, 2, 1, 2]
    layers = [ceil(el * depth_mult) for el in layers]
    strides = sum([[el] + [1] * (layers[i] - 1) for i, el in enumerate(strides)], [])
    exp_ratios = [1] * layers[0] + [6] * sum(layers[1:])
    depth = sum(layers) * 3
    base_chs = initial_chs / width_mult if width_mult < 1.0 else initial_chs
    out_chs_list = []
    for _ in range(depth // 3):
        out_chs_list.append(make_divisible(round(base_chs * width_mult), divisor=ch_div))
        base_chs += final_chs / (depth // 3 * 1.0)
    se_ratios = [0.0] * (layers[0] + layers[1]) + [se_ratio] * sum(layers[2:])
    return list(zip(out_chs_list, exp_ratios, strides, se_ratios))


class RexNet(nnx.Module):
    """ReXNet with the reference's model contract (reference rexnet.py:243-470)."""

    def __init__(
            self,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            output_stride: int = 32,
            initial_chs: int = 16,
            final_chs: int = 180,
            width_mult: float = 1.0,
            depth_mult: float = 1.0,
            se_ratio: float = 1 / 12.0,
            ch_div: int = 1,
            act_layer: str = 'swish',
            dw_act_layer: str = 'relu6',
            drop_rate: float = 0.2,
            drop_path_rate: float = 0.0,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        assert output_stride == 32
        self.num_classes = num_classes
        self.drop_rate = drop_rate
        self.grad_checkpointing = False
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        stem_base_chs = 32 / width_mult if width_mult < 1.0 else 32
        stem_chs = make_divisible(round(stem_base_chs * width_mult), divisor=ch_div)
        self.stem = ConvNormAct(in_chans, stem_chs, 3, stride=2, act_layer=act_layer, **kw)

        block_cfg = _block_cfg(width_mult, depth_mult, initial_chs, final_chs, se_ratio, ch_div)
        feat_chs = [stem_chs]
        self.feature_info = []
        curr_stride = 2
        features = []
        num_blocks = len(block_cfg)
        prev_chs = stem_chs
        for block_idx, (chs, exp_ratio, stride, block_se) in enumerate(block_cfg):
            if stride > 1:
                fname = 'stem' if block_idx == 0 else f'features.{block_idx - 1}'
                self.feature_info += [dict(num_chs=feat_chs[-1], reduction=curr_stride, module=fname)]
            block_dpr = drop_path_rate * block_idx / (num_blocks - 1)
            features.append(LinearBottleneck(
                in_chs=prev_chs, out_chs=chs, exp_ratio=exp_ratio, stride=stride,
                se_ratio=block_se, ch_div=ch_div, act_layer=act_layer,
                dw_act_layer=dw_act_layer, drop_path=block_dpr, **kw))
            curr_stride *= stride
            prev_chs = chs
            feat_chs += [features[-1].feat_channels()]
        pen_chs = make_divisible(1280 * width_mult, divisor=ch_div)
        self.feature_info += [dict(
            num_chs=feat_chs[-1], reduction=curr_stride, module=f'features.{len(features) - 1}')]
        features.append(ConvNormAct(prev_chs, pen_chs, act_layer=act_layer, **kw))
        self.features = nnx.List(features)
        self.num_features = self.head_hidden_size = pen_chs
        self.head = ClassifierHead(self.num_features, num_classes, global_pool, drop_rate, **kw)

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return set()

    def group_matcher(self, coarse: bool = False):
        return dict(stem=r'^stem', blocks=r'^features\.(\d+)')

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def get_classifier(self):
        return self.head.fc

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        self.head.reset(num_classes, pool_type=global_pool, rngs=rngs)

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        from ._manipulate import checkpoint_seq
        x = self.stem(x)
        if self.grad_checkpointing:
            x = checkpoint_seq(self.features, x)
        else:
            for f in self.features:
                x = f(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        return self.head(x, pre_logits=pre_logits)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, norm: bool = False, stop_early: bool = False,
            output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt == 'NHWC'
        # feature entries address stride-change boundaries recorded in
        # feature_info; map them onto flat feature-block indices
        module_ids = []
        for fi in self.feature_info:
            m = fi['module']
            module_ids.append(-1 if m == 'stem' else int(m.split('.')[1]))
        take_indices, max_index = feature_take_indices(len(module_ids), indices)
        take_blocks = {module_ids[i]: i for i in take_indices}
        max_block = module_ids[max_index]
        x = self.stem(x)
        intermediates = []
        if -1 in take_blocks:
            intermediates.append(x)
        for i, f in enumerate(self.features):
            if stop_early and i > max_block:
                break
            x = f(x)
            if i in take_blocks:
                intermediates.append(x)
        if intermediates_only:
            return intermediates
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        module_ids = [
            -1 if fi['module'] == 'stem' else int(fi['module'].split('.')[1])
            for fi in self.feature_info]
        take_indices, max_index = feature_take_indices(len(module_ids), indices)
        self.features = nnx.List(list(self.features)[:module_ids[max_index] + 1])
        if prune_head:
            self.reset_classifier(0, '')
        return take_indices


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    return convert_torch_state_dict(state_dict, model)


def _create_rexnet(variant, pretrained=False, **kwargs):
    return build_model_with_cfg(
        RexNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(flatten_sequential=True),
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bicubic',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'stem.conv', 'classifier': 'head.fc',
        'license': 'mit',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'rexnet_100.nav_in1k': _cfg(hf_hub_id='timm/'),
    'rexnet_130.nav_in1k': _cfg(hf_hub_id='timm/'),
    'rexnet_150.nav_in1k': _cfg(hf_hub_id='timm/'),
    'rexnet_200.nav_in1k': _cfg(hf_hub_id='timm/'),
    'rexnet_300.nav_in1k': _cfg(hf_hub_id='timm/'),
    'rexnetr_100.untrained': _cfg(),
    'rexnetr_130.untrained': _cfg(),
    'rexnetr_150.untrained': _cfg(),
    'rexnetr_200.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95,
                                         test_input_size=(3, 288, 288), test_crop_pct=1.0),
    'rexnetr_300.sw_in12k_ft_in1k': _cfg(hf_hub_id='timm/', crop_pct=0.95,
                                         test_input_size=(3, 288, 288), test_crop_pct=1.0),
})


@register_model
def rexnet_100(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnet_100', pretrained, **kwargs)


@register_model
def rexnet_130(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnet_130', pretrained, width_mult=1.3, **kwargs)


@register_model
def rexnet_150(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnet_150', pretrained, width_mult=1.5, **kwargs)


@register_model
def rexnet_200(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnet_200', pretrained, width_mult=2.0, **kwargs)


@register_model
def rexnet_300(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnet_300', pretrained, width_mult=3.0, **kwargs)


@register_model
def rexnetr_100(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnetr_100', pretrained, ch_div=8, **kwargs)


@register_model
def rexnetr_130(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnetr_130', pretrained, width_mult=1.3, ch_div=8, **kwargs)


@register_model
def rexnetr_150(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnetr_150', pretrained, width_mult=1.5, ch_div=8, **kwargs)


@register_model
def rexnetr_200(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnetr_200', pretrained, width_mult=2.0, ch_div=8, **kwargs)


@register_model
def rexnetr_300(pretrained=False, **kwargs) -> RexNet:
    return _create_rexnet('rexnetr_300', pretrained, width_mult=3.0, ch_div=16, **kwargs)
