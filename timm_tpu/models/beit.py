"""BEiT: BERT Pre-Training of Image Transformers, TPU-native
(reference: timm/models/beit.py:1-1065).

BEiT v1/v2 share one trunk: a ViT with NO absolute position embedding,
per-block (or shared) relative position bias with three extra cls-token
buckets, decomposed q/v biases (k bias fixed at zero), and layer-scale
residuals. TPU-first notes: the rel-pos gather index is a trace-time numpy
constant (see layers/pos_embed_rel.py), so each block's bias is one static
gather fused into the attention logits; blocks are rematerialisable via
checkpoint_seq.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from flax import nnx

from ..layers import (
    DropPath, Dropout, LayerNorm, Mlp, PatchEmbed, SwiGLU,
    calculate_drop_path_rates, get_norm_layer, global_pool_nlc, to_2tuple,
    trunc_normal_, zeros_,
)
from ..layers.attention import scaled_dot_product_attention
from ..layers.drop import apply_drop_path, dropout_rng_key
from ..layers.pos_embed_rel import RelPosBias
from ._builder import build_model_with_cfg
from ._features import feature_take_indices
from ._manipulate import (
    BlockStackError, checkpoint_seq, drop_path_scan_inputs, resolve_block_scan,
    scan_block_stack, warn_scan_fallback,
)
from ._registry import generate_default_cfgs, register_model

__all__ = ['Beit', 'BeitBlock', 'BeitAttention']


class BeitAttention(nnx.Module):
    """MHSA with decomposed q/v bias and optional windowed rel-pos bias
    (reference beit.py:108-275)."""

    def __init__(
            self,
            dim: int,
            num_heads: int = 8,
            qkv_bias: bool = False,
            attn_drop: float = 0.0,
            proj_drop: float = 0.0,
            window_size: Optional[Tuple[int, int]] = None,
            attn_head_dim: Optional[int] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.num_heads = num_heads
        head_dim = attn_head_dim if attn_head_dim is not None else dim // num_heads
        all_head_dim = head_dim * num_heads
        self.head_dim = head_dim
        self.scale = head_dim ** -0.5

        linear = partial(
            nnx.Linear, dtype=dtype, param_dtype=param_dtype,
            kernel_init=trunc_normal_(std=0.02), bias_init=zeros_, rngs=rngs)
        self.qkv = linear(dim, all_head_dim * 3, use_bias=False)
        if qkv_bias:
            self.q_bias = nnx.Param(jnp.zeros((all_head_dim,), param_dtype))
            self.v_bias = nnx.Param(jnp.zeros((all_head_dim,), param_dtype))
        else:
            self.q_bias = None
            self.v_bias = None

        if window_size:
            # per-block rel-pos bias incl. cls buckets; table zero-init as in
            # the reference so pretraining parity holds at init
            self.rel_pos_bias = RelPosBias(
                window_size=to_2tuple(window_size), num_heads=num_heads, prefix_tokens=1,
                param_dtype=param_dtype, rngs=rngs)
            self.rel_pos_bias.relative_position_bias_table[...] = jnp.zeros_like(
                self.rel_pos_bias.relative_position_bias_table[...])
        else:
            self.rel_pos_bias = None

        self.attn_drop = Dropout(attn_drop, rngs=rngs)
        self.proj = linear(all_head_dim, dim)
        self.proj_drop = Dropout(proj_drop, rngs=rngs)

    def __call__(self, x, shared_rel_pos_bias=None):
        B, N, C = x.shape
        qkv = self.qkv(x)
        if self.q_bias is not None:
            bias = jnp.concatenate([
                self.q_bias[...], jnp.zeros_like(self.q_bias[...]), self.v_bias[...]])
            qkv = qkv + bias.astype(qkv.dtype)
        qkv = qkv.reshape(B, N, 3, self.num_heads, self.head_dim).transpose(2, 0, 3, 1, 4)
        from ..parallel import shard_activation
        q, k, v = (shard_activation(t, 'heads') for t in (qkv[0], qkv[1], qkv[2]))

        attn_bias = None
        if self.rel_pos_bias is not None:
            attn_bias = self.rel_pos_bias.get_bias()
            if shared_rel_pos_bias is not None:
                attn_bias = attn_bias + shared_rel_pos_bias
        elif shared_rel_pos_bias is not None:
            attn_bias = shared_rel_pos_bias

        if attn_bias is not None:
            attn_bias = jnp.broadcast_to(
                attn_bias.astype(jnp.float32), (B, self.num_heads, N, N))
        dropout_p = 0.0 if self.attn_drop.deterministic else self.attn_drop.rate
        dropout_key = dropout_rng_key(self.attn_drop) if dropout_p > 0.0 else None
        x = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, dropout_p=dropout_p, dropout_key=dropout_key,
            scale=self.scale, fused=False)
        x = shard_activation(x.transpose(0, 2, 1, 3).reshape(B, N, -1), 'hidden')
        x = self.proj(x)
        return self.proj_drop(x)


class BeitBlock(nnx.Module):
    """Pre-norm block w/ named gamma_1/gamma_2 layer scale (reference beit.py:277-391)."""

    def __init__(
            self,
            dim: int,
            num_heads: int,
            qkv_bias: bool = False,
            mlp_ratio: float = 4.0,
            scale_mlp: bool = False,
            swiglu_mlp: bool = False,
            proj_drop: float = 0.0,
            attn_drop: float = 0.0,
            drop_path: float = 0.0,
            init_values: Optional[float] = None,
            act_layer: Union[str, Callable] = 'gelu',
            norm_layer: Callable = LayerNorm,
            window_size: Optional[Tuple[int, int]] = None,
            attn_head_dim: Optional[int] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        self.norm1 = norm_layer(dim, rngs=rngs)
        self.attn = BeitAttention(
            dim, num_heads=num_heads, qkv_bias=qkv_bias, attn_drop=attn_drop,
            proj_drop=proj_drop, window_size=window_size, attn_head_dim=attn_head_dim,
            dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path1 = DropPath(drop_path, rngs=rngs)
        self.norm2 = norm_layer(dim, rngs=rngs)
        if swiglu_mlp:
            self.mlp = SwiGLU(
                dim, hidden_features=int(dim * mlp_ratio),
                norm_layer=norm_layer if scale_mlp else None, drop=proj_drop,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        else:
            self.mlp = Mlp(
                dim, hidden_features=int(dim * mlp_ratio), act_layer=act_layer,
                norm_layer=norm_layer if scale_mlp else None, drop=proj_drop,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        self.drop_path2 = DropPath(drop_path, rngs=rngs)

        if init_values:
            self.gamma_1 = nnx.Param(jnp.full((dim,), init_values, param_dtype))
            self.gamma_2 = nnx.Param(jnp.full((dim,), init_values, param_dtype))
        else:
            self.gamma_1 = None
            self.gamma_2 = None

    def __call__(self, x, shared_rel_pos_bias=None, drop_path_override=None):
        y = self.attn(self.norm1(x), shared_rel_pos_bias=shared_rel_pos_bias)
        if self.gamma_1 is not None:
            y = y * self.gamma_1[...].astype(y.dtype)
        x = x + apply_drop_path(y, self.drop_path1, drop_path_override, 0)
        y = self.mlp(self.norm2(x))
        if self.gamma_2 is not None:
            y = y * self.gamma_2[...].astype(y.dtype)
        x = x + apply_drop_path(y, self.drop_path2, drop_path_override, 1)
        return x


class Beit(nnx.Module):
    """BEiT with the reference's full model contract (reference beit.py:448-905)."""

    def __init__(
            self,
            img_size: Union[int, Tuple[int, int]] = 224,
            patch_size: Union[int, Tuple[int, int]] = 16,
            in_chans: int = 3,
            num_classes: int = 1000,
            global_pool: str = 'avg',
            embed_dim: int = 768,
            depth: int = 12,
            num_heads: int = 12,
            qkv_bias: bool = True,
            mlp_ratio: float = 4.0,
            swiglu_mlp: bool = False,
            scale_mlp: bool = False,
            drop_rate: float = 0.0,
            pos_drop_rate: float = 0.0,
            proj_drop_rate: float = 0.0,
            attn_drop_rate: float = 0.0,
            drop_path_rate: float = 0.0,
            norm_layer: Optional[Union[str, Callable]] = None,
            init_values: Optional[float] = None,
            use_abs_pos_emb: bool = True,
            use_rel_pos_bias: bool = False,
            use_shared_rel_pos_bias: bool = False,
            head_init_scale: float = 0.001,
            block_scan: Optional[bool] = None,
            *,
            dtype=None,
            param_dtype=jnp.float32,
            rngs: nnx.Rngs,
    ):
        norm_layer = get_norm_layer(norm_layer) or LayerNorm
        self.num_classes = num_classes
        self.global_pool = global_pool
        self.num_features = self.head_hidden_size = self.embed_dim = embed_dim
        self.num_prefix_tokens = 1
        self.grad_checkpointing = False
        self.block_scan = resolve_block_scan(block_scan)

        self.patch_embed = PatchEmbed(
            img_size=img_size, patch_size=patch_size, in_chans=in_chans,
            embed_dim=embed_dim, dtype=dtype, param_dtype=param_dtype, rngs=rngs)
        num_patches = self.patch_embed.num_patches
        r = self.patch_embed.patch_size[0]

        self.cls_token = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, 1, embed_dim), param_dtype))
        self.pos_embed = nnx.Param(
            trunc_normal_(std=0.02)(rngs.params(), (1, num_patches + 1, embed_dim), param_dtype)) \
            if use_abs_pos_emb else None
        self.pos_drop = Dropout(pos_drop_rate, rngs=rngs)

        if use_shared_rel_pos_bias:
            self.rel_pos_bias = RelPosBias(
                window_size=self.patch_embed.grid_size, num_heads=num_heads, prefix_tokens=1,
                param_dtype=param_dtype, rngs=rngs)
            self.rel_pos_bias.relative_position_bias_table[...] = jnp.zeros_like(
                self.rel_pos_bias.relative_position_bias_table[...])
        else:
            self.rel_pos_bias = None

        dpr = calculate_drop_path_rates(drop_path_rate, depth)
        self.blocks = nnx.List([
            BeitBlock(
                dim=embed_dim,
                num_heads=num_heads,
                qkv_bias=qkv_bias,
                mlp_ratio=mlp_ratio,
                scale_mlp=scale_mlp,
                swiglu_mlp=swiglu_mlp,
                proj_drop=proj_drop_rate,
                attn_drop=attn_drop_rate,
                drop_path=dpr[i],
                norm_layer=norm_layer,
                init_values=init_values,
                window_size=self.patch_embed.grid_size if use_rel_pos_bias else None,
                dtype=dtype,
                param_dtype=param_dtype,
                rngs=rngs,
            )
            for i in range(depth)
        ])
        self.feature_info = [
            dict(module=f'blocks.{i}', num_chs=embed_dim, reduction=r) for i in range(depth)]

        use_fc_norm = global_pool == 'avg'
        self.norm = None if use_fc_norm else norm_layer(embed_dim, rngs=rngs)
        self.fc_norm = norm_layer(embed_dim, rngs=rngs) if use_fc_norm else None
        self.head_drop = Dropout(drop_rate, rngs=rngs)
        if num_classes > 0:
            self.head = nnx.Linear(
                embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02), bias_init=zeros_,
                dtype=dtype, param_dtype=param_dtype, rngs=rngs)
            if head_init_scale:
                self.head.kernel[...] = self.head.kernel[...] * head_init_scale
                self.head.bias[...] = self.head.bias[...] * head_init_scale
        else:
            self.head = None

        # BEiT depth-rescaled init
        for layer_id, block in enumerate(self.blocks):
            scale = math.sqrt(2.0 * (layer_id + 1))
            block.attn.proj.kernel[...] = block.attn.proj.kernel[...] / scale
            block.mlp.fc2.kernel[...] = block.mlp.fc2.kernel[...] / scale

        self._dtype = dtype
        self._param_dtype = param_dtype

    # -- contract ------------------------------------------------------------
    def no_weight_decay(self) -> set:
        return {'pos_embed', 'cls_token', 'relative_position_bias_table'}

    def group_matcher(self, coarse: bool = False):
        return dict(
            stem=r'^cls_token|pos_embed|patch_embed|rel_pos_bias',
            blocks=[(r'^blocks\.(\d+)', None), (r'^norm', (99999,))],
        )

    def set_grad_checkpointing(self, enable: bool = True):
        self.grad_checkpointing = enable

    def set_block_scan(self, enable: bool = True):
        """Toggle scan-over-layers block execution (see VisionTransformer)."""
        self.block_scan = enable

    def get_classifier(self):
        return self.head

    def reset_classifier(self, num_classes: int, global_pool: Optional[str] = None, *, rngs=None):
        self.num_classes = num_classes
        if global_pool is not None:
            self.global_pool = global_pool
        rngs = rngs if rngs is not None else nnx.Rngs(0)
        self.head = nnx.Linear(
            self.embed_dim, num_classes, kernel_init=trunc_normal_(std=0.02),
            dtype=self._dtype, param_dtype=self._param_dtype, rngs=rngs,
        ) if num_classes > 0 else None

    # -- forward -------------------------------------------------------------
    def forward_features(self, x):
        x = self.patch_embed(x)
        B = x.shape[0]
        cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)
        x = self.pos_drop(x)

        shared_bias = self.rel_pos_bias.get_bias() if self.rel_pos_bias is not None else None
        if self.block_scan:
            try:
                dp = drop_path_scan_inputs(self.blocks)

                def call(blk, xx, extra):
                    return blk(xx, shared_rel_pos_bias=shared_bias, drop_path_override=extra)

                x = scan_block_stack(
                    self.blocks, x, call, per_layer=dp, remat=self.grad_checkpointing)
                if self.norm is not None:
                    x = self.norm(x)
                return x
            except BlockStackError as e:
                warn_scan_fallback(type(self).__name__, e)
        from ..parallel import shard_activation
        x = shard_activation(x, 'residual')
        if self.grad_checkpointing:
            if shared_bias is None:
                x = checkpoint_seq(self.blocks, x)
            else:
                # remat per block with the shared bias as a traced arg so nnx
                # graph handling sees the module directly (not via a partial)
                remat_block = nnx.remat(lambda blk, x_, b: blk(x_, shared_rel_pos_bias=b))
                for blk in self.blocks:
                    x = shard_activation(remat_block(blk, x, shared_bias), 'residual')
        else:
            for blk in self.blocks:
                x = shard_activation(blk(x, shared_rel_pos_bias=shared_bias), 'residual')
        if self.norm is not None:
            x = self.norm(x)
        return x

    def forward_head(self, x, pre_logits: bool = False):
        x = global_pool_nlc(x, pool_type=self.global_pool, num_prefix_tokens=self.num_prefix_tokens)
        if self.fc_norm is not None:
            x = self.fc_norm(x)
        x = self.head_drop(x)
        if pre_logits or self.head is None:
            return x
        return self.head(x)

    def __call__(self, x):
        return self.forward_head(self.forward_features(x))

    def forward_intermediates(
            self, x, indices=None, return_prefix_tokens: bool = False, norm: bool = False,
            stop_early: bool = False, output_fmt: str = 'NHWC', intermediates_only: bool = False,
    ):
        assert output_fmt in ('NHWC', 'NLC')
        reshape = output_fmt == 'NHWC'
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        B, H, W, _ = x.shape
        grid = self.patch_embed.grid_size
        x = self.patch_embed(x)
        cls = jnp.broadcast_to(self.cls_token[...].astype(x.dtype), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        if self.pos_embed is not None:
            x = x + self.pos_embed[...].astype(x.dtype)
        x = self.pos_drop(x)
        shared_bias = self.rel_pos_bias.get_bias() if self.rel_pos_bias is not None else None

        intermediates = []
        blocks = self.blocks if not stop_early else list(self.blocks)[:max_index + 1]
        for i, blk in enumerate(blocks):
            x = blk(x, shared_rel_pos_bias=shared_bias)
            if i in take_indices:
                intermediates.append(self.norm(x) if (norm and self.norm is not None) else x)

        prefix_tokens = [y[:, 0:self.num_prefix_tokens] for y in intermediates]
        intermediates = [y[:, self.num_prefix_tokens:] for y in intermediates]
        if reshape:
            intermediates = [y.reshape(B, grid[0], grid[1], -1) for y in intermediates]
        if return_prefix_tokens:
            intermediates = list(zip(intermediates, prefix_tokens))
        if intermediates_only:
            return intermediates
        if self.norm is not None:
            x = self.norm(x)
        return x, intermediates

    def prune_intermediate_layers(self, indices=1, prune_norm: bool = False, prune_head: bool = True):
        take_indices, max_index = feature_take_indices(len(self.blocks), indices)
        self.blocks = nnx.List(list(self.blocks)[:max_index + 1])
        if prune_norm:
            self.norm = None
        if prune_head:
            self.fc_norm = None
            self.reset_classifier(0, '')
        return take_indices


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000,
        'input_size': (3, 224, 224),
        'pool_size': None,
        'crop_pct': 0.9,
        'interpolation': 'bicubic',
        'fixed_input_size': True,
        'mean': (0.5, 0.5, 0.5),
        'std': (0.5, 0.5, 0.5),
        'first_conv': 'patch_embed.proj',
        'classifier': 'head',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'beit_base_patch16_224.in22k_ft_in22k_in1k': _cfg(hf_hub_id='timm/'),
    'beit_base_patch16_384.in22k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'beit_large_patch16_224.in22k_ft_in22k_in1k': _cfg(hf_hub_id='timm/'),
    'beit_large_patch16_384.in22k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 384, 384), crop_pct=1.0),
    'beit_large_patch16_512.in22k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', input_size=(3, 512, 512), crop_pct=1.0),
    'beitv2_base_patch16_224.in1k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'beitv2_large_patch16_224.in1k_ft_in22k_in1k': _cfg(
        hf_hub_id='timm/', crop_pct=0.95, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225)),
    'test_beit.untrained': _cfg(input_size=(3, 96, 96)),
})


def checkpoint_filter_fn(state_dict, model):
    from ._torch_convert import convert_torch_state_dict
    out = {}
    for k, v in state_dict.items():
        if 'relative_position_index' in k or k in ('mask_token',):
            continue
        # torch keeps per-attn tables at attn.relative_position_bias_table;
        # ours nest inside attn.rel_pos_bias
        k = k.replace('attn.relative_position_bias_table', 'attn.rel_pos_bias.relative_position_bias_table')
        out[k] = v
    return convert_torch_state_dict(out, model)


def _create_beit(variant: str, pretrained: bool = False, **kwargs) -> Beit:
    out_indices = kwargs.pop('out_indices', 3)
    return build_model_with_cfg(
        Beit, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=out_indices),
        **kwargs,
    )


@register_model
def beit_base_patch16_224(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, mlp_ratio=4,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=0.1)
    return _create_beit('beit_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit_base_patch16_384(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        img_size=384, patch_size=16, embed_dim=768, depth=12, num_heads=12,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=0.1)
    return _create_beit('beit_base_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit_large_patch16_224(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('beit_large_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit_large_patch16_384(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        img_size=384, patch_size=16, embed_dim=1024, depth=24, num_heads=16,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('beit_large_patch16_384', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beit_large_patch16_512(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        img_size=512, patch_size=16, embed_dim=1024, depth=24, num_heads=16,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('beit_large_patch16_512', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beitv2_base_patch16_224(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        patch_size=16, embed_dim=768, depth=12, num_heads=12, mlp_ratio=4,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('beitv2_base_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def beitv2_large_patch16_224(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        patch_size=16, embed_dim=1024, depth=24, num_heads=16,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('beitv2_large_patch16_224', pretrained=pretrained, **dict(model_args, **kwargs))


@register_model
def test_beit(pretrained=False, **kwargs) -> Beit:
    model_args = dict(
        img_size=96, patch_size=16, embed_dim=64, depth=2, num_heads=2, mlp_ratio=3,
        use_abs_pos_emb=False, use_rel_pos_bias=True, init_values=1e-5)
    return _create_beit('test_beit', pretrained=pretrained, **dict(model_args, **kwargs))
