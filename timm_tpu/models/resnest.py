"""ResNeSt: Split-Attention ResNets, TPU-native NHWC
(reference: timm/models/resnest.py:1-270; Zhang et al. 2020).

ResNet trunk with Split-Attention 3x3 convs (timm_tpu/layers/split_attn.py)
and the 'avd' average-pool stride placement.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import nnx

from ..layers import BatchNormAct2d, SplitAttn, create_conv2d, get_act_fn
from ..layers.drop import DropPath
from ._builder import build_model_with_cfg
from ._registry import generate_default_cfgs, register_model
from .resnet import ResNet, checkpoint_filter_fn

__all__ = ['ResNestBottleneck']


def _avg_pool3_pad1(x, stride: int):
    """AvgPool2d(3, stride, padding=1), count_include_pad=True (torch default
    kept by the reference)."""
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    s = jax.lax.reduce_window(
        xp, 0.0, jax.lax.add, (1, 3, 3, 1), (1, stride, stride, 1), 'VALID')
    return s / 9.0


class ResNestBottleneck(nnx.Module):
    """(reference resnest.py:23-130)."""
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, radix=1,
                 cardinality=1, base_width=64, avd=False, avd_first=False,
                 reduce_first=1, dilation=1, first_dilation=None,
                 act_layer='relu', norm_layer: Callable = BatchNormAct2d,
                 attn_layer=None, aa_layer=None, drop_path=0.0,
                 *, dtype=None, param_dtype=jnp.float32, rngs: nnx.Rngs):
        assert reduce_first == 1
        assert attn_layer is None, 'attn_layer not supported by ResNestBottleneck'
        assert aa_layer is None, 'aa_layer not supported by ResNestBottleneck'
        group_width = int(planes * (base_width / 64.0)) * cardinality
        first_dilation = first_dilation or dilation
        # reference passes is_first per block; it's exactly "this block has a
        # downsample or strides", both of which our builder gives block 0
        is_first = stride > 1 or downsample is not None
        if avd and (stride > 1 or is_first):
            self.avd_stride = stride
            stride = 1
        else:
            self.avd_stride = 0
        self.avd_first = avd_first
        self.radix = radix
        kw = dict(dtype=dtype, param_dtype=param_dtype, rngs=rngs)

        self.conv1 = create_conv2d(inplanes, group_width, 1, **kw)
        self.bn1 = norm_layer(group_width, act_layer=act_layer, **kw)
        if radix >= 1:
            self.conv2 = SplitAttn(
                group_width, group_width, kernel_size=3, stride=stride,
                dilation=first_dilation, groups=cardinality, radix=radix,
                norm_layer=norm_layer, **kw)
            self.bn2 = None
        else:
            self.conv2 = create_conv2d(
                group_width, group_width, 3, stride=stride, dilation=first_dilation,
                groups=cardinality, padding=None, **kw)
            self.bn2 = norm_layer(group_width, act_layer=act_layer, **kw)
        self.conv3 = create_conv2d(group_width, planes * 4, 1, **kw)
        self.bn3 = norm_layer(planes * 4, apply_act=False, **kw)
        self.act = get_act_fn(act_layer)
        self.downsample = downsample
        self.drop_path = DropPath(drop_path, rngs=rngs)

    def zero_init_last(self):
        if hasattr(self.bn3, 'scale'):
            self.bn3.scale[...] = jnp.zeros_like(self.bn3.scale[...])

    def __call__(self, x):
        shortcut = x
        out = self.bn1(self.conv1(x))
        if self.avd_stride > 0 and self.avd_first:
            out = _avg_pool3_pad1(out, self.avd_stride)
        out = self.conv2(out)
        if self.bn2 is not None:
            out = self.bn2(out)
        if self.avd_stride > 0 and not self.avd_first:
            out = _avg_pool3_pad1(out, self.avd_stride)
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            shortcut = self.downsample(x)
        out = self.drop_path(out) + shortcut
        return self.act(out)


def _create_resnest(variant, pretrained=False, **kwargs):
    block_args = kwargs.pop('block_args', {})
    block = partial(ResNestBottleneck, **block_args) if block_args else ResNestBottleneck
    block.expansion = ResNestBottleneck.expansion
    return build_model_with_cfg(
        ResNet, variant, pretrained,
        pretrained_filter_fn=checkpoint_filter_fn,
        feature_cfg=dict(out_indices=(0, 1, 2, 3, 4)),
        block=block,
        **kwargs,
    )


def _cfg(url: str = '', **kwargs) -> Dict[str, Any]:
    return {
        'url': url,
        'num_classes': 1000, 'input_size': (3, 224, 224), 'pool_size': (7, 7),
        'crop_pct': 0.875, 'interpolation': 'bilinear',
        'mean': (0.485, 0.456, 0.406), 'std': (0.229, 0.224, 0.225),
        'first_conv': 'conv1.0', 'classifier': 'head.fc',
        **kwargs,
    }


default_cfgs = generate_default_cfgs({
    'resnest14d.gluon_in1k': _cfg(hf_hub_id='timm/'),
    'resnest26d.gluon_in1k': _cfg(hf_hub_id='timm/'),
    'resnest50d.in1k': _cfg(hf_hub_id='timm/'),
    'resnest101e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 256, 256), pool_size=(8, 8)),
    'resnest200e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 320, 320), pool_size=(10, 10), crop_pct=0.909),
    'resnest269e.in1k': _cfg(hf_hub_id='timm/', input_size=(3, 416, 416), pool_size=(13, 13), crop_pct=0.928),
    'resnest50d_4s2x40d.in1k': _cfg(hf_hub_id='timm/'),
    'resnest50d_1s4x24d.in1k': _cfg(hf_hub_id='timm/'),
})


@register_model
def resnest14d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(1, 1, 1, 1), stem_type='deep', stem_width=32, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest14d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest26d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(2, 2, 2, 2), stem_type='deep', stem_width=32, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest26d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest50d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 6, 3), stem_type='deep', stem_width=32, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest50d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest101e(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 23, 3), stem_type='deep', stem_width=64, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest101e', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest200e(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 24, 36, 3), stem_type='deep', stem_width=64, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest200e', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest269e(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 30, 48, 8), stem_type='deep', stem_width=64, avg_down=True,
        base_width=64, cardinality=1, block_args=dict(radix=2, avd=True, avd_first=False))
    return _create_resnest('resnest269e', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest50d_4s2x40d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 6, 3), stem_type='deep', stem_width=32, avg_down=True,
        base_width=40, cardinality=2, block_args=dict(radix=4, avd=True, avd_first=True))
    return _create_resnest('resnest50d_4s2x40d', pretrained, **dict(model_args, **kwargs))


@register_model
def resnest50d_1s4x24d(pretrained=False, **kwargs) -> ResNet:
    model_args = dict(
        layers=(3, 4, 6, 3), stem_type='deep', stem_width=32, avg_down=True,
        base_width=24, cardinality=4, block_args=dict(radix=1, avd=True, avd_first=True))
    return _create_resnest('resnest50d_1s4x24d', pretrained, **dict(model_args, **kwargs))
